"""In-memory coordination plane — the kube-apiserver analogue.

The reference's communication backend #1 is the kube-apiserver
(watch/list/patch; SURVEY.md §2.3). Its hermetic test tier gets a REAL
apiserver via envtest (suite_test.go:74-101). This build keeps the same
shape with an in-process object store + watch feed: controllers reconcile
against `KubeStore` exactly as they would against a cluster, and a real
kube client can replace it 1:1 (same method surface).
"""

from __future__ import annotations

import itertools
import threading
from typing import Callable, Optional

from ..apis.nodetemplate import NodeTemplate
from ..apis.provisioner import Provisioner
from ..models.cluster import PodDisruptionBudget, StateNode
from ..models.machine import Machine
from ..models.pod import PodSpec


class Conflict(Exception):
    pass


class Fenced(Conflict):
    """Write rejected: the presented fencing epoch is older than one the
    store has already observed — a deposed leader's late write."""


class KubeStore:
    """Typed object buckets with list/get/create/update/delete + watchers."""

    KINDS = ("pods", "nodes", "machines", "provisioners", "nodetemplates",
             "pdbs", "configmaps", "leases", "events", "intents")

    def __init__(self):
        self._lock = threading.RLock()
        self._objects: "dict[str, dict[str, object]]" = {k: {} for k in self.KINDS}
        self._watchers: "list[Callable[[str, str, object], None]]" = []
        self._rv = itertools.count(1)
        # admission interception point (set by Operator with the Webhooks
        # pipeline): fn(kind, obj, operation) -> obj, raising to reject —
        # the apiserver's admission-webhook call site analogue
        self._admission: "Optional[Callable[[str, object, str], object]]" = None
        # fencing: the highest leadership epoch this store has observed.
        # Lease writes carrying an `epoch` advance it atomically with the
        # leadership change itself; mutations presenting a stale epoch are
        # rejected (the zombie ex-leader's late write).
        self._fence_epoch = 0
        self.fenced_writes_rejected = 0

    def fence_epoch(self) -> int:
        with self._lock:
            return self._fence_epoch

    def _check_fence(self, kind: str, name: str, epoch: "Optional[int]",
                     obj=None) -> None:
        """Must run under self._lock, before the write is applied."""
        if epoch is not None:
            if epoch < self._fence_epoch:
                self.fenced_writes_rejected += 1
                raise Fenced(
                    f"{kind}/{name}: fencing epoch {epoch} < "
                    f"{self._fence_epoch} (deposed leader)")
            self._fence_epoch = epoch
        if kind == "leases":
            lease_epoch = getattr(obj, "epoch", None)
            if isinstance(lease_epoch, int) and lease_epoch > self._fence_epoch:
                self._fence_epoch = lease_epoch

    def set_admission(self, fn: "Optional[Callable[[str, object, str], object]]") -> None:
        with self._lock:
            self._admission = fn

    # -- generic ---------------------------------------------------------------

    def _notify(self, kind: str, action: str, obj) -> None:
        for w in list(self._watchers):
            try:
                w(kind, action, obj)
            except Exception:
                pass

    def watch(self, fn: Callable[[str, str, object], None]) -> None:
        """fn(kind, action in {added, modified, deleted}, object)."""
        with self._lock:
            self._watchers.append(fn)

    def unwatch(self, fn: Callable[[str, str, object], None]) -> None:
        """Deregister a watcher (a stopped HA replica sharing this store
        must not keep receiving events — and being kept alive — forever)."""
        with self._lock:
            self._watchers = [w for w in self._watchers if w is not fn]

    def create(self, kind: str, name: str, obj,
               epoch: "Optional[int]" = None) -> None:
        if self._admission is not None:
            obj = self._admission(kind, obj, "CREATE")
        with self._lock:
            self._check_fence(kind, name, epoch, obj)
            bucket = self._objects[kind]
            if name in bucket:
                raise Conflict(f"{kind}/{name} already exists")
            bucket[name] = obj
        self._notify(kind, "added", obj)

    def update(self, kind: str, name: str, obj,
               epoch: "Optional[int]" = None) -> None:
        if self._admission is not None:
            obj = self._admission(kind, obj, "UPDATE")
        with self._lock:
            self._check_fence(kind, name, epoch, obj)
            self._objects[kind][name] = obj
        self._notify(kind, "modified", obj)

    def get(self, kind: str, name: str):
        with self._lock:
            return self._objects[kind].get(name)

    def compare_and_swap(self, kind: str, name: str, expect, obj,
                         epoch: "Optional[int]" = None) -> None:
        """Atomic update iff the stored object is still `expect` (identity —
        the apiserver's resourceVersion-precondition analogue). Raises
        Conflict when another writer won the race. Leader-election leases
        depend on this being one critical section. Admission runs exactly as
        it does for update(): a real apiserver applies webhooks to
        precondition-guarded writes too."""
        if self._admission is not None:
            obj = self._admission(kind, obj, "UPDATE")
        with self._lock:
            self._check_fence(kind, name, epoch, obj)
            cur = self._objects[kind].get(name)
            if cur is not expect:
                raise Conflict(f"{kind}/{name} changed since read")
            self._objects[kind][name] = obj
        self._notify(kind, "modified", obj)

    def delete_if(self, kind: str, name: str, expect,
                  epoch: "Optional[int]" = None) -> bool:
        """Atomic delete iff the stored object is still `expect` (graceful
        lease release must not clobber a successor's lease)."""
        with self._lock:
            self._check_fence(kind, name, epoch)
            cur = self._objects[kind].get(name)
            if cur is not expect:
                return False
            self._objects[kind].pop(name)
        self._notify(kind, "deleted", expect)
        return True

    def delete(self, kind: str, name: str, epoch: "Optional[int]" = None):
        with self._lock:
            self._check_fence(kind, name, epoch)
            obj = self._objects[kind].pop(name, None)
        if obj is not None:
            self._notify(kind, "deleted", obj)
        return obj

    def list(self, kind: str) -> list:
        with self._lock:
            return list(self._objects[kind].values())

    # -- typed convenience -----------------------------------------------------

    def pods(self) -> "list[PodSpec]":
        return self.list("pods")

    def pending_pods(self) -> "list[PodSpec]":
        """Unschedulable pods: unbound non-daemon pods (the provisioning
        controller's watch predicate)."""
        return [p for p in self.pods() if not p.node_name and not p.is_daemon()]

    def daemon_pods(self) -> "list[PodSpec]":
        return [p for p in self.pods() if p.is_daemon()]

    def cordon_node(self, name: str) -> None:
        """Server-side cordon analogue: flips the stored node's deletion
        mark (our model's unschedulable bit) and notifies watchers. Over
        HttpKubeStore this is a spec.unschedulable merge-PATCH."""
        self._set_unschedulable(name, True)

    def uncordon_node(self, name: str) -> None:
        self._set_unschedulable(name, False)

    def _set_unschedulable(self, name: str, value: bool) -> None:
        with self._lock:
            node = self._objects["nodes"].get(name)
            if node is not None:
                node.marked_for_deletion = value
        if node is not None:
            self._notify("nodes", "modified", node)

    def bind_pod(self, pod_name: str, node_name: str,
                 epoch: "Optional[int]" = None) -> None:
        import dataclasses

        with self._lock:
            self._check_fence("pods", pod_name, epoch)
            pod = self._objects["pods"].get(pod_name)
            if pod is None:
                return
            if pod.node_name:
                raise Conflict(f"pod {pod_name} already bound to {pod.node_name}")
            bound = dataclasses.replace(pod, node_name=node_name)
            self._objects["pods"][pod_name] = bound
        self._notify("pods", "modified", bound)

    def nodes(self) -> "list[StateNode]":
        return self.list("nodes")

    def machines(self) -> "list[Machine]":
        return self.list("machines")

    def provisioners(self) -> "list[Provisioner]":
        return self.list("provisioners")

    def nodetemplates(self) -> "list[NodeTemplate]":
        return self.list("nodetemplates")

    def pdbs(self) -> "list[PodDisruptionBudget]":
        return self.list("pdbs")


class FencedKube:
    """Per-writer view of a KubeStore carrying that writer's fencing token.

    Reads (and everything else) pass straight through; the mutating surface
    presents `epoch_fn()` so the store can reject a deposed leader's late
    writes. Each replica wraps the SHARED store in its own view — the token
    travels with the caller, as a real apiserver request header would, not
    with the store.
    """

    def __init__(self, store: KubeStore, epoch_fn: "Callable[[], Optional[int]]"):
        self._store = store
        self._epoch_fn = epoch_fn

    def __getattr__(self, name):
        return getattr(self._store, name)

    def create(self, kind: str, name: str, obj) -> None:
        self._store.create(kind, name, obj, epoch=self._epoch_fn())

    def update(self, kind: str, name: str, obj) -> None:
        self._store.update(kind, name, obj, epoch=self._epoch_fn())

    def compare_and_swap(self, kind: str, name: str, expect, obj) -> None:
        self._store.compare_and_swap(kind, name, expect, obj,
                                     epoch=self._epoch_fn())

    def delete_if(self, kind: str, name: str, expect) -> bool:
        return self._store.delete_if(kind, name, expect,
                                     epoch=self._epoch_fn())

    def delete(self, kind: str, name: str):
        return self._store.delete(kind, name, epoch=self._epoch_fn())

    def bind_pod(self, pod_name: str, node_name: str) -> None:
        self._store.bind_pod(pod_name, node_name, epoch=self._epoch_fn())
