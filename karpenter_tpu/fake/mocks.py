"""Behavior-injection primitives for the fake cloud.

Parity target: /root/reference/pkg/fake/types.go:21-76 — `MockedFunction[I,O]`
(override output, default output, call counting) and `AtomicError` (one-shot
or N-times error injection) used by every fake API.
"""

from __future__ import annotations

import threading
from typing import Callable, Generic, Optional, TypeVar

I = TypeVar("I")
O = TypeVar("O")


class AtomicError:
    """Error served up to `times` calls (fake/atomic.go:80-106)."""

    def __init__(self, err: Exception, times: int = 1):
        self.err = err
        self.times = times
        self._calls = 0
        self._lock = threading.Lock()

    def get(self) -> Optional[Exception]:
        with self._lock:
            if self._calls >= self.times:
                return None
            self._calls += 1
            return self.err


class MockedFunction(Generic[I, O]):
    def __init__(self, name: str, default_fn: Callable[[I], O]):
        self.name = name
        self.default_fn = default_fn
        self.output: Optional[O] = None
        self.error: Optional[AtomicError] = None
        self.calls: "list[I]" = []
        self._lock = threading.Lock()

    @property
    def called_with_count(self) -> int:
        with self._lock:
            return len(self.calls)

    def set_error(self, err: Exception, times: int = 1) -> None:
        self.error = AtomicError(err, times)

    def invoke(self, request: I) -> O:
        with self._lock:
            self.calls.append(request)
        if self.error is not None:
            err = self.error.get()
            if err is not None:
                raise err
        if self.output is not None:
            return self.output
        return self.default_fn(request)

    def reset(self) -> None:
        with self._lock:
            self.calls.clear()
        self.output = None
        self.error = None
