"""Mini kube-apiserver: a real HTTP server speaking enough of the
Kubernetes REST API to exercise the coordination plane over the wire.

This is the in-repo kwok/envtest analogue (reference test infrastructure,
/root/reference/test/pkg/environment/ + envtest in unit suites): the
HttpKubeStore client, the deploy/ manifests, and the controller CLI can all
run against it without a cluster.

Supported surface (JSON only):

- CRUD + LIST on core (`/api/v1/...`) and group (`/apis/{g}/{v}/...`)
  paths, namespaced and cluster-scoped;
- `?watch=true` chunked watch streams (initial ADDED replay + live events),
  one JSON object per line, with resourceVersion bookkeeping;
- optimistic concurrency: PUT with metadata.resourceVersion must match or
  409 (the CAS substrate for leader-election leases);
- the pod `binding` subresource (POST .../pods/{name}/binding) setting
  spec.nodeName, 409 when already bound;
- fencing (mirror of fake/kube.py): mutating requests may present their
  leadership epoch in an `X-Fencing-Epoch` header — an epoch older than
  the server's high-water mark is refused with 409 Fenced before the
  write applies, and lease documents carrying an `epoch` advance the
  high-water atomically with the leadership change itself.

State is plural-keyed documents; the server neither validates schemas nor
runs admission — that stays client/controller-side, exactly where the
framework's webhook pipeline sits.
"""

from __future__ import annotations

import json
import queue
import re
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional

# path -> (plural); both /api/v1 (core) and /apis/{group}/{version} forms.
_PATH_RE = re.compile(
    r"^/(?:api/v1|apis/[^/]+/[^/]+)"
    r"(?:/namespaces/(?P<ns>[^/]+))?"
    r"/(?P<plural>[^/?]+)"
    r"(?:/(?P<name>[^/?]+))?"
    r"(?:/(?P<sub>binding|status))?$")


class _State:
    def __init__(self):
        self.lock = threading.Lock()
        self.objects: "dict[str, dict[str, dict]]" = {}
        self.rv = 0
        self.watchers: "dict[str, list[queue.Queue]]" = {}
        # fencing: highest leadership epoch any request has presented (or
        # any lease write has carried); stale writers get 409 Fenced
        self.fence_epoch = 0
        self.fenced_writes_rejected = 0

    def bucket(self, plural: str) -> "dict[str, dict]":
        return self.objects.setdefault(plural, {})

    def next_rv(self) -> str:
        self.rv += 1
        return str(self.rv)

    def notify(self, plural: str, type_: str, doc: dict) -> None:
        for q in self.watchers.get(plural, []):
            q.put({"type": type_, "object": doc})

    def add_watcher(self, plural: str) -> "queue.Queue":
        q: "queue.Queue" = queue.Queue()
        self.watchers.setdefault(plural, []).append(q)
        return q

    def drop_watcher(self, plural: str, q) -> None:
        ws = self.watchers.get(plural, [])
        if q in ws:
            ws.remove(q)


class _Handler(BaseHTTPRequestHandler):
    protocol_version = "HTTP/1.1"
    disable_nagle_algorithm = True  # response segments must not wait out
    # the client's delayed ACK (the keep-alive client sets TCP_NODELAY too)
    state: _State  # injected by serve()

    def log_message(self, *args):  # quiet
        pass

    # -- helpers ---------------------------------------------------------------

    def _json(self, code: int, doc) -> None:
        body = json.dumps(doc).encode()
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        # advertise the fencing high-water mark on every response so
        # clients can track it passively (HttpKubeStore.fence_epoch)
        self.send_header("X-Fencing-Epoch", str(self.state.fence_epoch))
        self.end_headers()
        self.wfile.write(body)

    def _error(self, code: int, reason: str, message: str) -> None:
        self._json(code, {"kind": "Status", "status": "Failure",
                          "reason": reason, "message": message, "code": code})

    def _read_body(self) -> dict:
        n = int(self.headers.get("Content-Length", 0))
        return json.loads(self.rfile.read(n) or b"{}")

    def _route(self):
        path, _, query = self.path.partition("?")
        m = _PATH_RE.match(path)
        if m is None:
            return None
        return m.group("plural"), m.group("name"), m.group("sub"), query

    def _fence_rejects(self, plural: str, name: "str | None",
                       body: "dict | None" = None) -> bool:
        """Mirror of KubeStore._check_fence over the wire. Caller holds
        state.lock. Returns True when the request was refused (the 409 is
        already on the wire); a fresh epoch advances the high-water mark."""
        st = self.state
        hdr = self.headers.get("X-Fencing-Epoch")
        if hdr is not None:
            try:
                epoch = int(hdr)
            except ValueError:
                self._error(422, "Invalid",
                            f"X-Fencing-Epoch {hdr!r} is not an integer")
                return True
            if epoch < st.fence_epoch:
                st.fenced_writes_rejected += 1
                self._error(409, "Fenced",
                            f"{plural}/{name}: fencing epoch {epoch} < "
                            f"{st.fence_epoch} (deposed leader)")
                return True
            st.fence_epoch = epoch
        if plural == "leases" and isinstance(body, dict):
            spec = body.get("spec")
            lease_epoch = (spec.get("epoch") if isinstance(spec, dict)
                           else body.get("epoch"))
            if isinstance(lease_epoch, int) and lease_epoch > st.fence_epoch:
                st.fence_epoch = lease_epoch
        return False

    # -- verbs -----------------------------------------------------------------

    def do_GET(self):
        r = self._route()
        if r is None:
            return self._error(404, "NotFound", self.path)
        plural, name, _sub, query = r
        st = self.state
        if name is None and "watch=true" in query:
            return self._watch(plural)
        with st.lock:
            bucket = st.bucket(plural)
            if name is None:
                items = list(bucket.values())
                return self._json(200, {"kind": "List", "items": items,
                                        "metadata": {"resourceVersion": str(st.rv)}})
            doc = bucket.get(name)
        if doc is None:
            return self._error(404, "NotFound", f"{plural}/{name}")
        return self._json(200, doc)

    def _watch(self, plural: str) -> None:
        st = self.state
        with st.lock:
            q = st.add_watcher(plural)
            initial = list(st.bucket(plural).values())
        try:
            self.send_response(200)
            self.send_header("Content-Type", "application/json")
            self.send_header("Transfer-Encoding", "chunked")
            self.end_headers()

            def emit(event: dict) -> None:
                line = (json.dumps(event) + "\n").encode()
                self.wfile.write(f"{len(line):x}\r\n".encode() + line + b"\r\n")
                self.wfile.flush()

            for doc in initial:
                emit({"type": "ADDED", "object": doc})
            while True:
                try:
                    emit(q.get(timeout=1.0))
                except queue.Empty:
                    emit({"type": "BOOKMARK", "object": {}})  # liveness tick
        except (BrokenPipeError, ConnectionResetError, OSError):
            pass
        finally:
            with st.lock:
                st.drop_watcher(plural, q)

    def do_POST(self):
        body = self._read_body()  # drain BEFORE any early reply: leftover
        # body bytes corrupt the next request's framing on keep-alive
        r = self._route()
        if r is None:
            return self._error(404, "NotFound", self.path)
        plural, name, sub, _ = r
        st = self.state
        if sub == "binding":
            target = ((body.get("target") or {}).get("name")
                      or body.get("nodeName", ""))
            with st.lock:
                if self._fence_rejects(plural, name):
                    return None
                doc = st.bucket(plural).get(name)
                if doc is None:
                    return self._error(404, "NotFound", f"{plural}/{name}")
                spec = doc.setdefault("spec", {})
                if spec.get("nodeName"):
                    return self._error(
                        409, "Conflict",
                        f"pod {name} already bound to {spec['nodeName']}")
                spec["nodeName"] = target
                doc["metadata"]["resourceVersion"] = st.next_rv()
                st.notify(plural, "MODIFIED", doc)
            return self._json(201, {"kind": "Status", "status": "Success"})
        obj_name = (body.get("metadata") or {}).get("name") or name
        if not obj_name:
            return self._error(422, "Invalid", "metadata.name required")
        with st.lock:
            if self._fence_rejects(plural, obj_name, body):
                return None
            bucket = st.bucket(plural)
            if obj_name in bucket:
                return self._error(409, "AlreadyExists",
                                   f"{plural}/{obj_name} already exists")
            body.setdefault("metadata", {})["name"] = obj_name
            body["metadata"]["resourceVersion"] = st.next_rv()
            bucket[obj_name] = body
            st.notify(plural, "ADDED", body)
        return self._json(201, body)

    def do_PUT(self):
        body = self._read_body()  # drain before any early reply (framing)
        r = self._route()
        if r is None or r[1] is None:
            return self._error(404, "NotFound", self.path)
        plural, name, _sub, _ = r
        st = self.state
        want_rv = (body.get("metadata") or {}).get("resourceVersion")
        with st.lock:
            if self._fence_rejects(plural, name, body):
                return None
            bucket = st.bucket(plural)
            cur = bucket.get(name)
            if cur is not None and want_rv is not None \
                    and cur["metadata"].get("resourceVersion") != want_rv:
                return self._error(409, "Conflict",
                                   f"{plural}/{name} resourceVersion mismatch")
            body.setdefault("metadata", {})["name"] = name
            body["metadata"]["resourceVersion"] = st.next_rv()
            bucket[name] = body
            st.notify(plural, "MODIFIED" if cur is not None else "ADDED", body)
        return self._json(200, body)

    def do_PATCH(self):
        """application/merge-patch+json (RFC 7386): recursive merge, null
        deletes a key — the subset real clients (and HttpKubeStore's
        cordon) use. A /status PATCH scopes to the status portion like the
        real subresource; other content types get 415."""
        patch = self._read_body()  # drain before any early reply (framing)
        r = self._route()
        if r is None or r[1] is None:
            return self._error(404, "NotFound", self.path)
        plural, name, sub, _ = r
        if self.headers.get("Content-Type") != "application/merge-patch+json":
            return self._error(
                415, "UnsupportedMediaType",
                "only application/merge-patch+json is implemented")
        if sub not in (None, "", "status"):
            return self._error(405, "MethodNotAllowed",
                               f"PATCH on subresource {sub!r} not supported")
        st = self.state
        if not isinstance(patch, dict):
            return self._error(415, "UnsupportedMediaType",
                               "merge-patch body must be a JSON object")
        if sub == "status":
            patch = {"status": patch.get("status", {})}

        def merge(base, over):
            out = dict(base)
            for k, v in over.items():
                if v is None:
                    out.pop(k, None)
                elif isinstance(v, dict) and isinstance(out.get(k), dict):
                    out[k] = merge(out[k], v)
                else:
                    out[k] = v
            return out

        with st.lock:
            if self._fence_rejects(plural, name, patch):
                return None
            bucket = st.bucket(plural)
            cur = bucket.get(name)
            if cur is None:
                return self._error(404, "NotFound", f"{plural}/{name}")
            body = merge(cur, patch)
            body.setdefault("metadata", {})["name"] = name
            body["metadata"]["resourceVersion"] = st.next_rv()
            bucket[name] = body
            st.notify(plural, "MODIFIED", body)
        return self._json(200, body)

    def do_DELETE(self):
        body = self._read_body()  # drain before any early reply (framing)
        r = self._route()
        if r is None or r[1] is None:
            return self._error(404, "NotFound", self.path)
        plural, name, _sub, _ = r
        st = self.state
        want_rv = (body.get("preconditions") or {}).get("resourceVersion")
        with st.lock:
            if self._fence_rejects(plural, name):
                return None
            cur = st.bucket(plural).get(name)
            if cur is not None and want_rv is not None \
                    and cur["metadata"].get("resourceVersion") != want_rv:
                return self._error(409, "Conflict",
                                   f"{plural}/{name} resourceVersion mismatch")
            doc = st.bucket(plural).pop(name, None)
            if doc is not None:
                st.notify(plural, "DELETED", doc)
        if doc is None:
            return self._error(404, "NotFound", f"{plural}/{name}")
        return self._json(200, doc)


def serve(address: str = "127.0.0.1", port: int = 0
          ) -> "tuple[ThreadingHTTPServer, int, _State]":
    """Start the mini apiserver; returns (server, bound_port, state)."""
    state = _State()
    handler = type("Handler", (_Handler,), {"state": state})
    srv = ThreadingHTTPServer((address, port), handler)
    t = threading.Thread(target=srv.serve_forever, daemon=True,
                         name="mini-apiserver")
    t.start()
    return srv, srv.server_address[1], state
