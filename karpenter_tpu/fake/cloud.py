"""Stateful fake cloud backend — the hermetic test substrate AND the simulated
provisioning API for local runs.

Parity target: /root/reference/pkg/fake/ec2api.go — stateful CreateFleet
honoring InsufficientCapacityPools (:37-41,106-120), instance store (:62-64
sync.Maps), launch-template store, subnet/SG fixtures, plus SSM/Pricing fakes.
API shapes are our own TPU-cloud flavor (SURVEY.md §2.3: "GCP/TPU provisioning
APIs or simulated backend"), not EC2's wire format.
"""

from __future__ import annotations

import dataclasses
import os
import threading
from typing import Optional, Sequence

from ..models.instancetype import Catalog
from ..utils import errors as cloud_errors
from ..utils.clock import Clock
from .mocks import MockedFunction


@dataclasses.dataclass
class CloudInstance:
    id: str
    instance_type: str
    zone: str
    capacity_type: str
    state: str = "running"  # pending|running|stopping|stopped|shutting-down|terminated
    tags: "dict[str, str]" = dataclasses.field(default_factory=dict)
    launch_time: float = 0.0
    image_id: str = ""
    subnet_id: str = ""
    launch_template: str = ""
    # private DNS name the node registers with under the default ip-name
    # convention (settings nodeNameConvention; reference instanceToMachine
    # lowercases PrivateDnsName, cloudprovider.go:344-348)
    private_dns: str = ""


@dataclasses.dataclass(frozen=True)
class FleetOverride:
    instance_type: str
    zone: str
    subnet_id: str = ""
    price: float = 0.0
    # per-override launch template (multi-arch fleets: one LT per arch,
    # reference getLaunchTemplateConfigs instance.go:289-323); empty uses
    # the request default
    launch_template: str = ""


@dataclasses.dataclass
class CreateFleetRequest:
    launch_template: str
    overrides: "list[FleetOverride]"
    capacity: int
    capacity_type: str
    tags: "dict[str, str]" = dataclasses.field(default_factory=dict)
    image_id: str = ""
    # EC2 Fleet "context" (reserved-capacity targeting; the reference passes
    # nodeTemplate.Spec.Context verbatim, instance.go:228)
    fleet_context: str = ""


@dataclasses.dataclass
class FleetPoolError:
    code: str
    instance_type: str
    zone: str


@dataclasses.dataclass
class CreateFleetResponse:
    instance_ids: "list[str]"
    errors: "list[FleetPoolError]" = dataclasses.field(default_factory=list)


@dataclasses.dataclass
class Subnet:
    id: str
    zone: str
    free_ips: int = 1000
    tags: "dict[str, str]" = dataclasses.field(default_factory=dict)


@dataclasses.dataclass
class SecurityGroup:
    id: str
    name: str
    tags: "dict[str, str]" = dataclasses.field(default_factory=dict)


@dataclasses.dataclass
class Image:
    id: str
    name: str
    arch: str = "amd64"
    created: float = 0.0
    tags: "dict[str, str]" = dataclasses.field(default_factory=dict)


@dataclasses.dataclass
class LaunchTemplate:
    name: str
    image_id: str
    userdata: str = ""
    tags: "dict[str, str]" = dataclasses.field(default_factory=dict)
    # resolved node-template options (reference carries these in the EC2 LT
    # data: metadataOptions, blockDeviceMappings, monitoring, instance profile
    # — launchtemplate.go:195-235 createLaunchTemplate)
    metadata_options: "dict" = dataclasses.field(default_factory=dict)
    block_devices: "list[dict]" = dataclasses.field(default_factory=list)
    monitoring: bool = False
    instance_profile: str = ""
    security_group_ids: "list[str]" = dataclasses.field(default_factory=list)


class FakeCloud:
    """In-memory cloud. `Reset()` between tests (ec2api.go:76-104 discipline)."""

    def __init__(self, catalog: Optional[Catalog] = None, clock: Optional[Clock] = None):
        self.clock = clock or Clock()
        self.catalog = catalog
        self.lock = threading.RLock()
        self.instances: "dict[str, CloudInstance]" = {}
        self.launch_templates: "dict[str, LaunchTemplate]" = {}
        self.subnets: "list[Subnet]" = [
            Subnet(id=f"subnet-{z}", zone=z, free_ips=1000 - 10 * i)
            for i, z in enumerate(("zone-1a", "zone-1b", "zone-1c"))
        ]
        self.security_groups: "list[SecurityGroup]" = [
            SecurityGroup(id="sg-default", name="default",
                          tags={"kubernetes.io/cluster/test-cluster": "owned"}),
        ]
        self.images: "list[Image]" = [
            Image(id="img-amd64-1", name="node-image-amd64-v1", arch="amd64", created=1.0),
            Image(id="img-amd64-2", name="node-image-amd64-v2", arch="amd64", created=2.0),
            Image(id="img-arm64-1", name="node-image-arm64-v1", arch="arm64", created=1.0),
        ]
        self.ssm_parameters: "dict[str, str]" = {
            "/karpenter-tpu/images/default/amd64/latest": "img-amd64-2",
            "/karpenter-tpu/images/default/arm64/latest": "img-arm64-1",
        }
        # (capacity_type, instance_type, zone) triples that synthesize ICE
        self.insufficient_capacity_pools: "set[tuple[str, str, str]]" = set()
        self._next_id = 1

        self.create_fleet_api: MockedFunction = MockedFunction(
            "CreateFleet", self._create_fleet)
        self.describe_instances_api: MockedFunction = MockedFunction(
            "DescribeInstances", self._describe_instances)
        self.terminate_instances_api: MockedFunction = MockedFunction(
            "TerminateInstances", self._terminate_instances)

    # -- fleet ---------------------------------------------------------------

    def create_fleet(self, request: CreateFleetRequest) -> CreateFleetResponse:
        return self.create_fleet_api.invoke(request)

    def _create_fleet(self, request: CreateFleetRequest) -> CreateFleetResponse:
        with self.lock:
            lts_used = {o.launch_template or request.launch_template
                        for o in request.overrides}
            lts_used.discard("")
            if request.launch_template:
                lts_used.add(request.launch_template)
            for lt in lts_used:
                if lt not in self.launch_templates:
                    raise cloud_errors.CloudError(
                        cloud_errors.LAUNCH_TEMPLATE_NOT_FOUND,
                        f"launch template {lt} not found")
            # lowest-price allocation across overrides, skipping ICE pools
            # (EC2 CreateFleet lowest-price / fake ec2api.go:106-120)
            errors: "list[FleetPoolError]" = []
            usable: "list[FleetOverride]" = []
            for o in sorted(request.overrides, key=lambda o: (o.price, o.instance_type, o.zone)):
                if (request.capacity_type, o.instance_type, o.zone) in self.insufficient_capacity_pools:
                    errors.append(FleetPoolError(
                        "InsufficientInstanceCapacity", o.instance_type, o.zone))
                    continue
                usable.append(o)
            ids = []
            if usable:
                choice = usable[0]
                lt_name = choice.launch_template or request.launch_template
                lt = self.launch_templates.get(lt_name)
                for _ in range(request.capacity):
                    n = self._next_id
                    self._next_id += 1
                    iid = f"i-{n:08d}"
                    self.instances[iid] = CloudInstance(
                        id=iid,
                        private_dns=f"ip-10-{(n >> 16) & 255}-{(n >> 8) & 255}"
                                    f"-{n & 255}.internal",
                        instance_type=choice.instance_type,
                        zone=choice.zone,
                        capacity_type=request.capacity_type,
                        state="pending",
                        tags=dict(request.tags),
                        launch_time=self.clock.now(),
                        image_id=request.image_id or (lt.image_id if lt else ""),
                        subnet_id=choice.subnet_id,
                        launch_template=lt_name,
                    )
                    ids.append(iid)
            return CreateFleetResponse(instance_ids=ids, errors=errors)

    # -- instances -----------------------------------------------------------

    def describe_instances(self, ids: Sequence[str]) -> "list[CloudInstance]":
        return self.describe_instances_api.invoke(tuple(ids))

    def _describe_instances(self, ids) -> "list[CloudInstance]":
        with self.lock:
            out = []
            for i in ids:
                inst = self.instances.get(i)
                if inst is not None and inst.state != "terminated":
                    # instances become visible-running on the 2nd describe
                    # (eventual consistency analogue, instance.go:98-107)
                    if inst.state == "pending":
                        inst.state = "running"
                    out.append(dataclasses.replace(inst, tags=dict(inst.tags)))
            return out

    def create_tags(self, instance_id: str, tags: "dict[str, str]") -> None:
        with self.lock:
            inst = self.instances.get(instance_id)
            if inst is None:
                raise cloud_errors.CloudError(
                    "InvalidInstanceID.NotFound", instance_id)
            inst.tags.update(tags)

    def describe_instances_by_tag(self, key: str, value: str) -> "list[CloudInstance]":
        with self.lock:
            return [dataclasses.replace(i, tags=dict(i.tags))
                    for i in self.instances.values()
                    if i.tags.get(key) == value and i.state != "terminated"]

    def terminate_instances(self, ids: Sequence[str]) -> "list[tuple[str, str]]":
        return self.terminate_instances_api.invoke(tuple(ids))

    def _terminate_instances(self, ids) -> "list[tuple[str, str]]":
        with self.lock:
            out = []
            for i in ids:
                inst = self.instances.get(i)
                if inst is None:
                    raise cloud_errors.CloudError(
                        "InvalidInstanceID.NotFound", f"instance {i} not found")
                inst.state = "terminated"
                out.append((i, "terminated"))
            return out

    # -- launch templates ----------------------------------------------------

    def create_launch_template(self, lt: LaunchTemplate) -> None:
        with self.lock:
            self.launch_templates[lt.name] = lt

    def describe_launch_templates(self, tag_key: str = "", tag_value: str = "") -> "list[LaunchTemplate]":
        with self.lock:
            return [lt for lt in self.launch_templates.values()
                    if not tag_key or lt.tags.get(tag_key) == tag_value]

    def delete_launch_template(self, name: str) -> None:
        with self.lock:
            if name not in self.launch_templates:
                raise cloud_errors.CloudError(
                    cloud_errors.LAUNCH_TEMPLATE_NOT_FOUND, name)
            del self.launch_templates[name]

    # -- discovery -----------------------------------------------------------

    def describe_subnets(self, selector: "dict[str, str]") -> "list[Subnet]":
        with self.lock:
            return [s for s in self.subnets if _match_selector(s.tags, s.id, selector)]

    def describe_security_groups(self, selector: "dict[str, str]") -> "list[SecurityGroup]":
        with self.lock:
            return [g for g in self.security_groups
                    if _match_selector(g.tags, g.id, selector)]

    def describe_images(self, selector: "dict[str, str]") -> "list[Image]":
        with self.lock:
            return [im for im in self.images if _match_selector(im.tags, im.id, selector)]

    def get_ssm_parameter(self, name: str) -> str:
        with self.lock:
            if name not in self.ssm_parameters:
                raise cloud_errors.CloudError("ResourceNotFound", name)
            return self.ssm_parameters[name]

    def get_prices(self) -> "dict[tuple[str, str, str], float]":
        """(instance_type, capacity_type, zone) -> $/h from the catalog."""
        out = {}
        if self.catalog is None:
            return out
        for t in self.catalog.types:
            for o in t.offerings:
                out[(t.name, o.capacity_type, o.zone)] = o.price
        return out

    def reset(self) -> None:
        with self.lock:
            self.instances.clear()
            self.launch_templates.clear()
            self.insufficient_capacity_pools.clear()
            for api in (self.create_fleet_api, self.describe_instances_api,
                        self.terminate_instances_api):
                api.reset()

    # -- account persistence ---------------------------------------------------
    # The simulated ACCOUNT (instances + launch templates + id watermark)
    # can round-trip through a JSON file so separate processes share one
    # account — `controller --simulate --state F` then `cleanup --state F`
    # behaves like the reference's test-account sweeper against real cloud
    # state. Static infra (subnets/SGs/images/prices) is derived config,
    # not account state, and is not persisted.

    def save_state(self, path: str) -> None:
        import json

        with self.lock:
            doc = {
                "instances": [dataclasses.asdict(i)
                              for i in self.instances.values()],
                "launch_templates": [dataclasses.asdict(lt)
                                     for lt in self.launch_templates.values()],
                "next_id": self._next_id,
            }
        # atomic replace with a per-writer temp name: a crash mid-write
        # must not corrupt the account, and two processes saving the shared
        # file concurrently must not interleave into one temp file
        import tempfile

        fd, tmp = tempfile.mkstemp(
            dir=os.path.dirname(os.path.abspath(path)) or ".",
            prefix=os.path.basename(path) + ".")
        try:
            with os.fdopen(fd, "w") as f:
                json.dump(doc, f, indent=1)
            os.replace(tmp, path)
        except BaseException:
            try:
                os.unlink(tmp)
            except OSError:
                pass
            raise

    def load_state(self, path: str) -> None:
        import json

        with open(path) as f:
            doc = json.load(f)
        with self.lock:
            self.instances = {
                d["id"]: CloudInstance(**d) for d in doc["instances"]}
            self.launch_templates = {
                d["name"]: LaunchTemplate(**d)
                for d in doc["launch_templates"]}
            self._next_id = int(doc["next_id"])


def _match_selector(tags: "dict[str, str]", obj_id: str, selector: "dict[str, str]") -> bool:
    """Tag/id selector semantics (subnet.go:87 getFilters): key 'id' matches
    the object id (comma-separated list ok), '*' values are wildcards."""
    if not selector:
        return False
    for k, v in selector.items():
        if k == "id":
            if obj_id not in [x.strip() for x in v.split(",")]:
                return False
        elif v == "*":
            if k not in tags:
                return False
        else:
            if tags.get(k) not in [x.strip() for x in v.split(",")]:
                return False
    return True
