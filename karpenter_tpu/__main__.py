"""CLI entry point.

Parity target: /root/reference/cmd/controller/main.go:33-65 (operator boot)
plus the new solver sidecar from SURVEY.md §7.1.

  python -m karpenter_tpu solver-serve --port 50151
      Host the TPU solver gRPC service (the solver half).

  python -m karpenter_tpu controller --simulate [--solver ADDR]
      Run the full controller plane against the simulated cloud backend
      (SURVEY.md §2.3: "GCP/TPU provisioning APIs or simulated backend").
      With --solver, scheduling solves go to the gRPC sidecar with the
      native/oracle fallback chain; without, the in-process TPU solver runs.

  python -m karpenter_tpu version
"""

from __future__ import annotations

import argparse
import logging
import os
import signal
import sys

from . import __version__ as VERSION


def _wait_for_signal() -> None:
    """Block until SIGTERM/SIGINT. Explicit handlers: the environment's
    sitecustomize can leave default SIGINT delivery unreliable, and
    orchestrators terminate with SIGTERM."""
    import threading

    stop = threading.Event()
    for sig in (signal.SIGTERM, signal.SIGINT):
        signal.signal(sig, lambda signum, frame: stop.set())
    while not stop.is_set():
        # poll rather than a bare wait(): signal handlers only run between
        # interpreter bytecodes, and Event.wait() without timeout parks in C
        stop.wait(0.2)


def cmd_solver_serve(args) -> int:
    if args.distributed:
        # MUST run before any import that touches the XLA backend (the
        # kernels are imported lazily below for exactly this reason)
        from .parallel.multihost import initialize_distributed, mesh_description, make_hybrid_mesh

        multi = initialize_distributed(args.coordinator, args.num_processes,
                                       args.process_id)
        print(f"distributed: {mesh_description(make_hybrid_mesh())}"
              if multi else "distributed requested but single-process",
              flush=True)
    from .solver.service import SolverService, serve

    # one switch for every device->host read the solvers perform
    # (solver/core.py host_fetch); unconditional so an explicit
    # `--readback get` overrides a KARPENTER_TPU_READBACK=callback env
    from .solver import core as solver_core

    solver_core._READBACK = args.readback
    service = SolverService(trace_dir=args.trace_dir or None,
                            trace_every=args.trace_every)
    server, port, _service = serve(f"{args.host}:{args.port}",
                                   max_workers=args.workers, service=service)
    print(f"solver service listening on {args.host}:{port}", flush=True)
    try:
        _wait_for_signal()
    finally:
        server.stop(grace=1.0)
    return 0


def cmd_fleet_replica(args) -> int:
    from .fleet.replica import run_replica_main

    return run_replica_main(args)


def cmd_controller(args) -> int:
    from .apis.nodetemplate import NodeTemplate
    from .apis.provisioner import Provisioner
    from .apis.settings import Settings
    from .fake.cloud import FakeCloud
    from .operator import Operator
    from .providers.instancetypes import generate_fleet_catalog

    if not args.simulate and not args.kubeconfig:
        print("need --simulate (in-process store) or --kubeconfig SERVER "
              "(real coordination plane; the cloud backend stays simulated — "
              "real TPU-fleet API wiring is environment-specific)",
              file=sys.stderr)
        return 2

    kube = None
    if args.kubeconfig:
        from .coordination.httpkube import HttpKubeStore

        kube = HttpKubeStore.from_kubeconfig(args.kubeconfig)
        kube.start()
        print(f"coordination plane: {kube.server} "
              f"({sum(len(kube.list(k)) for k in kube.KINDS)} objects synced)",
              flush=True)

    catalog = generate_fleet_catalog()
    settings = Settings(cluster_name=args.cluster_name,
                        cluster_endpoint="https://simulated")
    solver_factory = None
    if args.solver:
        from .solver.client import RemoteSolver

        # late-binding hub reference: the factory only runs during
        # reconcile cycles, after the Operator (and its ResilienceHub)
        # exists — so the remote solver edge shares the solver breaker
        # and retry budget with every other borrower
        _op_cell: "list" = []

        def solver_factory(cat, provs):
            if not _op_cell:
                # must not happen in the current boot order (the cell is
                # filled right after Operator construction); if a future
                # refactor constructs solvers eagerly, losing the breaker/
                # budget protection silently would be far worse than a log
                logging.getLogger("karpenter.cli").warning(
                    "solver factory ran before the Operator was "
                    "constructed: remote solver edge has NO resilience "
                    "hub (no breaker, no retry budget)")
            return RemoteSolver(
                cat, provs, target=args.solver,
                resilience=_op_cell[0].resilience if _op_cell else None)
    cloud = FakeCloud(catalog)
    if args.state and os.path.exists(args.state):
        cloud.load_state(args.state)
        print(f"loaded simulated account from {args.state} "
              f"({len(cloud.instances)} instances)", flush=True)
    # reference templates discover infra by cluster tag; tag the simulated
    # subnets/SGs so `karpenter.sh/discovery: <cluster>` selectors resolve
    for s in cloud.subnets:
        s.tags.setdefault("karpenter.sh/discovery", args.cluster_name)
    for g in cloud.security_groups:
        g.tags.setdefault("karpenter.sh/discovery", args.cluster_name)
    # each listener disables independently with -1; the plane exists if ANY
    # port is enabled
    serve_http = any(p >= 0 for p in (args.metrics_port, args.health_port,
                                      args.webhook_port))
    op = Operator(cloud, settings, catalog, kube=kube,
                  solver_factory=solver_factory,
                  solver_target=args.solver,
                  leader_elect=bool(args.leader_elect),
                  serve_http=serve_http,
                  metrics_port=args.metrics_port,
                  health_port=args.health_port,
                  webhook_port=args.webhook_port,
                  webhook_tls=(args.webhook_tls_cert, args.webhook_tls_key))
    if args.solver:
        _op_cell.append(op)
    if args.apply:
        # reference-compatible manifests (Provisioner / AWSNodeTemplate /
        # Deployment / Pod / PDB YAML) drive the plane as-is
        from .apis.yaml_compat import load_files

        loaded = load_files(*args.apply, env={"CLUSTER_NAME": args.cluster_name})
        for t in loaded.templates:
            op.kube.create("nodetemplates", t.name, t)
        for p in loaded.provisioners:
            op.kube.create("provisioners", p.name, p)
        for pdb in loaded.pdbs:
            op.kube.create("pdbs", pdb.name, pdb)  # flows to cluster via watch
        for pod in loaded.pods:
            op.kube.create("pods", pod.name, pod)
        print(f"applied {len(loaded.templates)} templates, "
              f"{len(loaded.provisioners)} provisioners, "
              f"{len(loaded.pods)} pods, {len(loaded.pdbs)} pdbs", flush=True)
    elif not args.kubeconfig:
        # simulate-only default seeding; against a real coordination plane
        # the cluster's own objects are authoritative
        # (kube.create runs the admission webhooks: defaulting + validation)
        op.kube.create("nodetemplates", "default", NodeTemplate(
            name="default",
            subnet_selector={"id": "subnet-zone-1a,subnet-zone-1b,subnet-zone-1c"},
            security_group_selector={"id": "sg-default"}))
        op.kube.create("provisioners", "default",
                       Provisioner(name="default", provider_ref="default"))
    op.start()
    print(f"controller running (cluster={args.cluster_name}, "
          f"solver={'grpc:' + args.solver if args.solver else 'in-process'}); "
          f"Ctrl-C to stop", flush=True)
    try:
        _wait_for_signal()
    finally:
        op.stop()
        if args.state:
            cloud.save_state(args.state)
            print(f"saved simulated account to {args.state}", flush=True)
    return 0


def cmd_cleanup(args) -> int:
    """Sweep leaked capacity: cloud instances with no coordination-plane
    owner and stale hash-named launch templates. The operational analogue of
    the reference's test-account cleanup tooling (reference test/ 'cleanup'
    + sweeper scripts) pointed at the framework's own GC logic — one
    explicit pass, printed, exit 0 (reconcile-once semantics; the running
    controller does this continuously)."""
    from .apis.settings import Settings
    from .cloudprovider import CloudProvider
    from .controllers.garbagecollection import GarbageCollectionController
    from .fake.cloud import FakeCloud
    from .fake.kube import KubeStore
    from .providers.instancetypes import generate_fleet_catalog

    if not args.state:
        # the cloud backend in this build is process-local (simulated); a
        # cleanup pointed at a real apiserver would compare its machines
        # against an EMPTY fresh cloud and retire healthy capacity. The
        # running controller's own GC loop is the live-cluster sweeper;
        # this command sweeps a PERSISTED simulated account (--state FILE,
        # the file `controller --simulate --state FILE` maintains).
        print("cleanup needs --state FILE (the persisted simulated account "
              "written by `controller --simulate --state FILE`); for a live "
              "cluster the controller's GC loop is the sweeper",
              file=sys.stderr)
        return 2
    if not os.path.exists(args.state):
        # a typo'd path must not silently sweep (and then persist) a fresh
        # empty account — the account file is the contract
        print(f"state file not found: {args.state}", file=sys.stderr)
        return 2
    kube = KubeStore()

    catalog = generate_fleet_catalog()
    settings = Settings(cluster_name=args.cluster_name,
                        cluster_endpoint="https://simulated")
    cloud = FakeCloud(catalog)
    cloud.load_state(args.state)
    n_before = len([i for i in cloud.instances.values()
                    if i.state == "running"])
    provider = CloudProvider(cloud, settings, catalog)
    gc = GarbageCollectionController(kube, provider)
    # force-expire the grace windows when asked: a cleanup sweep of a dead
    # test account wants everything, not just old leaks
    if args.all:
        gc.grace_seconds = 0
    reaped = gc.reconcile_once()
    stale_lts = provider.launch_templates.delete_all() \
        if args.launch_templates else 0
    cloud.save_state(args.state)
    print(f"account {args.state}: {n_before} running instance(s); "
          f"reaped {len(reaped)} leaked, {stale_lts} launch template(s)")
    for r in reaped:
        print(f"  {r}")
    return 0


def _tail_delta(lines: "list[str]", last_printed: "str | None"
                ) -> "tuple[list[str], str | None]":
    """New lines since `last_printed` in a SLIDING log window.

    The cursor is the last printed line's content, matched from the end:
    an index cursor goes permanently silent once the window fills (every
    poll returns exactly N lines), and if the marker rotated out entirely
    the whole window is new."""
    start = 0
    if last_printed is not None:
        for i in range(len(lines) - 1, -1, -1):
            if lines[i] == last_printed:
                start = i + 1
                break
    new = lines[start:]
    return new, (lines[-1] if lines else last_printed)


def cmd_logs(args) -> int:
    """Fetch recent controller logs from a live controller's /logz endpoint
    (utils/logring ring buffer) — the hermetic analogue of the reference's
    log-fetch tool (test/cmd/logs/main.go: controller logs for a test run
    without shelling into the pod). --follow polls for new lines."""
    import time as _time
    import urllib.request

    base = args.endpoint.rstrip("/")
    last_printed = None  # content cursor: /logz serves a SLIDING window,
    # so an index into it would go silent once the window fills
    while True:
        try:
            with urllib.request.urlopen(f"{base}/logz?n={args.lines}",
                                        timeout=10) as r:
                lines = [ln for ln in r.read().decode().splitlines() if ln]
        except OSError as e:
            if not args.follow:
                print(f"cannot reach {base}/logz: {e}", file=sys.stderr)
                return 1
            # tail -f survives controller restarts: retry, don't abort
            print(f"# retrying ({e})", file=sys.stderr)
            _time.sleep(args.interval)
            continue
        if not args.follow:
            for ln in lines:
                print(ln)
            return 0
        new, last_printed = _tail_delta(lines, last_printed)
        for ln in new:
            print(ln, flush=True)
        _time.sleep(args.interval)


def _fetch_json(url: str):
    import json as _json
    import urllib.request

    with urllib.request.urlopen(url, timeout=10) as r:
        return _json.loads(r.read().decode())


def cmd_statusz(args) -> int:
    """Pretty-print a live controller's /debug/statusz snapshot (the
    introspection plane's one-consistent-view; metrics listener)."""
    import json as _json

    base = args.endpoint.rstrip("/")
    try:
        snap = _fetch_json(f"{base}/debug/statusz")
    except OSError as e:
        print(f"cannot reach {base}/debug/statusz: {e}", file=sys.stderr)
        return 1
    print(_json.dumps(snap, indent=2, sort_keys=True, default=str))
    return 0


def cmd_diagnose(args) -> int:
    """Fetch a diagnostics bundle from a live controller (/debug/bundle:
    statusz ring + logs + traces + events + metrics text) and write it to
    --out, or stdout when no path is given. The offline counterpart is the
    bundle the flight recorder auto-writes on reconcile exceptions,
    watchdog deadman firings, and chaos invariant breaches."""
    import json as _json

    base = args.endpoint.rstrip("/")
    try:
        bundle = _fetch_json(f"{base}/debug/bundle")
    except OSError as e:
        print(f"cannot reach {base}/debug/bundle: {e}", file=sys.stderr)
        return 1
    text = _json.dumps(bundle, indent=2, sort_keys=True, default=str)
    if args.out:
        with open(args.out, "w") as f:
            f.write(text + "\n")
        ring = bundle.get("statusz_ring") or []
        trig = bundle.get("trigger") or {}
        print(f"bundle written to {args.out} "
              f"(trigger={trig.get('reason', '?')}, "
              f"snapshots={len(ring)})")
    else:
        print(text)
    return 0


def cmd_events(args) -> int:
    """Fetch the recent event ring from a live controller's /eventz
    endpoint (health listener) — `kubectl get events` shaped triage,
    mirroring the `logs` + /logz pair."""
    import json as _json

    base = args.endpoint.rstrip("/")
    try:
        payload = _fetch_json(f"{base}/eventz?n={args.count}")
    except OSError as e:
        print(f"cannot reach {base}/eventz: {e}", file=sys.stderr)
        return 1
    events = payload.get("events", [])
    if args.json:
        print(_json.dumps(events, indent=2, default=str))
        return 0
    for e in events:
        print(f"{e.get('ts', 0):.3f} {e.get('kind', ''):<7} "
              f"{e.get('reason', ''):<24} {e.get('object', ''):<32} "
              f"{e.get('message', '')}")
    if not events:
        print("no events recorded")
    return 0


def cmd_explain(args) -> int:
    """Answer WHY for a pod from a live controller's decision-provenance
    ring (/debug/decisions, the explain plane): the DecisionRecord that
    assigned the pod, or its per-dimension unschedulability attribution
    with the ranked reason summary. With no pod, prints the decision
    index (or one full record with --id)."""
    import json as _json
    from urllib.error import HTTPError

    base = args.endpoint.rstrip("/")
    if args.pod:
        url = f"{base}/debug/decisions?pod={args.pod}"
    elif args.id:
        url = f"{base}/debug/decisions?id={args.id}"
    else:
        url = f"{base}/debug/decisions?limit={args.limit}"
    try:
        payload = _fetch_json(url)
    except HTTPError as e:
        try:
            body = e.read().decode().strip()
        except Exception:  # noqa: BLE001 — CLI boundary
            body = ""
        print(body or f"{url}: {e}", file=sys.stderr)
        return 1
    except OSError as e:
        print(f"cannot reach {url}: {e}", file=sys.stderr)
        return 1
    if args.json or not args.pod:
        print(_json.dumps(payload, indent=2, sort_keys=True, default=str))
        return 0
    # human verdict for ONE pod: its assignment, or the ranked attribution
    rid = payload.get("id", "?")
    for a in payload.get("assignments", ()):
        if args.pod in (a.get("pods") or ()):
            print(f"pod {args.pod}: ASSIGNED by decision {rid} -> "
                  f"{a.get('itype')}/{a.get('zone')}/"
                  f"{a.get('capacity_type')} "
                  f"(provisioner {a.get('provisioner')}, "
                  f"${a.get('price', 0)}/h)")
            return 0
    for u in payload.get("unassigned", ()):
        if u.get("pod") == args.pod:
            print(f"pod {args.pod}: UNSCHEDULABLE (decision {rid})")
            print(f"  reason:  {u.get('reason')}")
            print(f"  summary: {u.get('summary')}")
            print(f"  ranked:  {', '.join(u.get('ranked') or ())}")
            nearest = u.get("nearest")
            if nearest:
                print(f"  nearest fit: short by {nearest.get('display')}")
            if not u.get("parity", True):
                print("  WARNING: attribution disagrees with the scalar "
                      "oracle (reason parity audit failed)")
            return 0
    print(f"pod {args.pod}: mentioned by decision {rid} "
          f"(kind {payload.get('kind')})")
    return 0


def cmd_sync(args) -> int:
    """Make a coordination plane match a manifest fixture set (apply +
    optional prune) — the hermetic analogue of the reference's GitOps
    test-cluster sync (test/cmd/sync-cluster; the synced path is
    test/infrastructure/clusters/test-infra)."""
    from .apis.yaml_compat import load_files
    from .coordination.sync import sync_manifests

    paths = []
    for p in args.manifests:
        if os.path.isdir(p):
            # recursive: fixture trees nest by kind (provisioners/,
            # workloads/ — pruning against a partial load would DELETE the
            # nested objects as "absent")
            for root, _dirs, files in sorted(os.walk(p)):
                paths.extend(sorted(
                    os.path.join(root, f) for f in files
                    if f.endswith((".yaml", ".yml"))))
        else:
            paths.append(p)
    if not paths:
        print("no manifests found", file=sys.stderr)
        return 2
    loaded = load_files(*paths, env={"CLUSTER_NAME": args.cluster_name})
    from .coordination.httpkube import HttpKubeStore

    kube = HttpKubeStore.from_kubeconfig(args.kubeconfig)
    kube.start()
    try:
        counts = sync_manifests(kube, loaded, prune=args.prune)
    finally:
        kube.stop()
    print(f"synced {len(paths)} file(s): {counts['created']} created, "
          f"{counts['updated']} updated, {counts['pruned']} pruned, "
          f"{counts['unchanged']} unchanged")
    return 0


def cmd_apiserver(args) -> int:
    """Boot the mini apiserver standalone and write a kubeconfig for it:
    the offline substrate of the getting-started walkthrough (the real
    alternative is envtest via hack/fetch_envtest.sh). Serves until ^C."""
    import time as _time

    from .fake.apiserver import serve

    srv, port, _state = serve(port=args.port)
    kubeconfig = {
        "apiVersion": "v1", "kind": "Config",
        "clusters": [{"name": "mini",
                      "cluster": {"server": f"http://127.0.0.1:{port}"}}],
        "users": [{"name": "mini", "user": {}}],
        "contexts": [{"name": "mini",
                      "context": {"cluster": "mini", "user": "mini"}}],
        "current-context": "mini",
    }
    import json as _json

    with open(args.write_kubeconfig, "w") as f:
        _json.dump(kubeconfig, f, indent=1)  # kubeconfigs are YAML, but
        # JSON is a YAML subset — every loader (ours + kubectl) accepts it
    print(f"mini apiserver listening on 127.0.0.1:{port}")
    print(f"kubeconfig written to {args.write_kubeconfig}")
    print("next: python -m karpenter_tpu controller --simulate "
          f"--kubeconfig {args.write_kubeconfig} --apply examples/quickstart.yaml")
    try:
        while True:
            _time.sleep(3600)
    except KeyboardInterrupt:
        srv.shutdown()
    return 0


def cmd_get(args) -> int:
    """kubectl-get analogue over the coordination plane: list a kind with
    the columns an operator checks first (the walkthrough's 'watch the
    nodes appear' step, no kubectl needed)."""
    from .coordination.httpkube import HttpKubeStore

    kube = HttpKubeStore.from_kubeconfig(args.kubeconfig)
    try:
        # one-shot LIST seed (reads come from the informer cache); no
        # watch threads needed for a point-in-time get
        kube._relist(args.kind)
        objs = kube.list(args.kind)
    except Exception as e:  # noqa: BLE001 — CLI boundary
        print(f"error: {e}", file=sys.stderr)
        return 1
    if not objs:
        print(f"no {args.kind} found")
        return 0
    try:
        for o in objs:
            name = getattr(o, "name", None) or getattr(
                o, "metadata", {}).get("name", "?")
            cols = [str(name)]
            labels = dict(getattr(o, "labels", ()) or {})
            if args.kind == "nodes":
                from .apis import wellknown as wk

                cols += [labels.get(wk.LABEL_INSTANCE_TYPE, ""),
                         labels.get(wk.LABEL_ZONE, ""),
                         labels.get(wk.LABEL_CAPACITY_TYPE, "")]
            elif args.kind == "pods":
                cols.append(getattr(o, "node_name", "") or "<pending>")
            print("  ".join(c for c in cols if c != ""))
    except BrokenPipeError:  # | head closed stdout mid-listing
        pass
    return 0


def _ledger_partition(artifact) -> None:
    """Ledger the partition drill's key numbers (remap fraction, recovery
    cycles, warm-state loss) so gen_docs citations and the perf trend have
    a source of truth. Best-effort: the ledger lives in benchmarks/, which
    an installed wheel may not carry."""
    try:
        from benchmarks import ledger
    except ImportError:
        return
    key = artifact["key_numbers"]
    workload = {"replicas": artifact["replicas"],
                "tenants": artifact["tenants"],
                "seed": artifact["seed"]}
    art = artifact.get("artifact_path")
    for metric, value, unit in (
            ("fleet_failover_remap_fraction",
             key["remap_fraction"], "fraction"),
            ("fleet_failover_recovery_to_green",
             key["recovery_to_green_cycles"], "cycles"),
            ("fleet_failover_warm_state_losses",
             key["warm_state_losses"], "count")):
        ledger.record(metric, value, unit, source="chaos-partition",
                      workload=workload, artifact=art)


def _ledger_spotstorm(artifact) -> None:
    """Ledger the spot-storm drill's key numbers (restore latency,
    proactive rebalances, cost delta) — same best-effort contract as
    _ledger_partition."""
    try:
        from benchmarks import ledger
    except ImportError:
        return
    art = artifact.get("artifact_path")
    # the SAME extractor backfill uses, so a later `backfill()` dedupes
    # against what the live run recorded (key = artifact+metric+workload)
    for (metric, value, unit, backend, degraded,
         workload, _ts) in ledger._spot_entries(artifact):
        ledger.record(metric, value, unit, source="chaos-spot-storm",
                      backend=backend, degraded=degraded,
                      workload=workload, artifact=art)


def cmd_chaos(args) -> int:
    """Seeded chaos sweep: drive faulted scenarios to convergence, check
    the cross-layer invariants, and write a replay artifact."""
    from .chaos import ChaosRunner

    runner = ChaosRunner(seed=args.seed, scenarios=args.scenarios,
                         intensity=args.intensity,
                         out_dir=args.out_dir or None,
                         burst=args.burst, crash=args.crash,
                         storm=args.storm, partition=args.partition,
                         spot_storm=args.spot_storm,
                         spot_storm_nodes=args.spot_nodes,
                         spot_storm_reclaims=args.spot_reclaims)
    artifact = runner.run()
    for s in artifact["scenarios"]:
        verdict = "PASS" if s["passed"] else "FAIL"
        if args.partition:
            if s["drill"] == "partition":
                t = s["totals"]
                print(f"seed={s['seed']} scenario={s['scenario']} {verdict} "
                      f"{s['drill']} remap={s['remap_fraction']} "
                      f"(~{s['remap_expected']}) served={t['served']} "
                      f"shed={t['shed_quarantine']} "
                      f"cold_remaps={t['cold_remaps']} "
                      f"epoch={s['membership_epoch']}")
            else:
                print(f"seed={s['seed']} scenario={s['scenario']} {verdict} "
                      f"{s['drill']} epoch={s['epoch']}")
        elif args.spot_storm:
            if s["drill"] == "spot-storm":
                print(f"seed={s['seed']} scenario={s['scenario']} {verdict} "
                      f"{s['drill']} nodes={s['fleet']['nodes']} "
                      f"reclaims={s['storm']['reclaims_delivered']} "
                      f"restore={s['storm']['restore_cycles']}"
                      f"/{s['storm']['restore_bound']} "
                      f"rebalances={len(s['rebalance']['ledger'])}")
            elif s["drill"] == "spot-wrong-forecast":
                print(f"seed={s['seed']} scenario={s['scenario']} {verdict} "
                      f"{s['drill']} reclaims={s['reclaims_delivered']} "
                      f"restore={s['restore_cycles']} "
                      f"post_clear_launches={s['post_clear_launches']}")
            else:
                print(f"seed={s['seed']} scenario={s['scenario']} {verdict} "
                      f"{s['drill']} decisions_identical="
                      f"{s['decisions_identical']}")
        elif args.storm:
            t = s["totals"]
            print(f"seed={s['seed']} scenario={s['scenario']} {verdict} "
                  f"tenants={s['tenants']} submitted={t['submitted']} "
                  f"served={t['served']} "
                  f"shed={t['shed_admission']}+{t['shed_queue']} "
                  f"mega_solves={s['mega_solves']} "
                  f"drain={s['drain_ticks']}")
        elif args.crash:
            print(f"seed={s['seed']} scenario={s['scenario']} {verdict} "
                  f"{s['drill']} crash_cycle={s.get('crash_cycle', '-')} "
                  f"replayed={len(s['replay'])} nodes={s['final_nodes']} "
                  f"settle={s['settle_cycles']}")
        else:
            print(f"seed={s['seed']} scenario={s['scenario']} {verdict} "
                  f"kinds={len(s['fired_kinds'])} "
                  f"layers={','.join(s['layers'])} "
                  f"nodes={s['final_nodes']} settle={s['settle_cycles']}")
        for v in s["violations"]:
            print(f"  VIOLATION [{v['invariant']}] {v['message']}")
    if artifact.get("artifact_path"):
        print(f"artifact: {artifact['artifact_path']}")
    for bundle in artifact.get("bundles", []):
        print(f"diagnostics bundle: {bundle} "
              f"(inspect: python -m karpenter_tpu diagnose, or read the "
              f"JSON directly)")
    if not artifact["passed"]:
        print(f"REPRODUCE: python -m karpenter_tpu chaos --seed {args.seed} "
              f"--scenarios {args.scenarios}"
              f"{' --burst' if args.burst else ''}"
              f"{' --crash' if args.crash else ''}"
              f"{' --storm' if args.storm else ''}"
              f"{' --partition' if args.partition else ''}"
              f"{' --spot-storm' if args.spot_storm else ''}")
        return 1
    if args.spot_storm:
        key = artifact["key_numbers"]
        print(f"chaos: spot storm passed — {key['fleet_nodes']} nodes, "
              f"{key['storm_reclaims']} simultaneous reclaim(s), capacity "
              f"restored in {key['restore_cycles']} cycle(s) (bound "
              f"{artifact['restore_bound_cycles']}), "
              f"{key['proactive_rebalances']} proactive rebalance(s), "
              f"cost ${key['hourly_cost_before']}/h -> "
              f"${key['hourly_cost_after']}/h "
              f"({artifact['duration_s']}s)")
        _ledger_spotstorm(artifact)
        return 0
    if args.partition:
        key = artifact["key_numbers"]
        print(f"chaos: partition drill passed — remap fraction "
              f"{key['remap_fraction']} (expected ~"
              f"{key['remap_expected']}), recovery to green in "
              f"{key['recovery_to_green_cycles']} cycle(s), "
              f"{key['warm_state_losses']} warm-state loss(es), "
              f"{key['poisons_quarantined']} poison(s) quarantined "
              f"({artifact['duration_s']}s)")
        _ledger_partition(artifact)
    elif args.storm:
        print(f"chaos: tenant storm passed — {artifact['scenario_count']} "
              f"scenario(s), {artifact['tenants']} tenants each, fairness "
              f"bound held ({artifact['duration_s']}s)")
    elif args.crash:
        print(f"chaos: crash drill passed — {artifact['scenario_count']} "
              f"scenario(s) across {len(artifact['crashpoints'])} "
              f"crashpoint(s) + leader failover "
              f"({artifact['duration_s']}s)")
    else:
        print(f"chaos: {artifact['scenario_count']} scenario(s) passed, "
              f"{len(artifact['fault_kinds'])} fault kinds across "
              f"{len(artifact['layers'])} layers "
              f"({artifact['duration_s']}s)")
    return 0


def main(argv=None) -> int:
    logging.basicConfig(
        level=logging.INFO,
        format="%(asctime)s %(levelname).1s %(name)s %(message)s")
    parser = argparse.ArgumentParser(prog="karpenter_tpu")
    sub = parser.add_subparsers(dest="command", required=True)

    p_serve = sub.add_parser("solver-serve", help="host the solver gRPC service")
    p_serve.add_argument("--host", default="127.0.0.1")
    p_serve.add_argument("--port", type=int, default=50151)
    p_serve.add_argument("--workers", type=int, default=4)
    p_serve.add_argument("--distributed", action="store_true",
                         help="join a multi-host mesh via jax.distributed")
    p_serve.add_argument("--coordinator", default=None,
                         help="coordinator address host:port (defaults from env)")
    p_serve.add_argument("--num-processes", type=int, default=None)
    p_serve.add_argument("--process-id", type=int, default=None)
    p_serve.add_argument("--trace-dir", default="",
                         help="capture a jax.profiler trace of every "
                              "--trace-every'th solve into this directory")
    p_serve.add_argument("--trace-every", type=int, default=100)
    p_serve.add_argument(
        "--readback", choices=("get", "callback"),
        default=os.environ.get("KARPENTER_TPU_READBACK", "get"),
        help="device->host readback transport: literal fetch (get) or "
             "io_callback streaming (callback) — for relays whose link "
             "degrades after the first literal read")
    p_serve.set_defaults(fn=cmd_solver_serve)

    p_replica = sub.add_parser(
        "fleet-replica",
        help="host ONE fleet solver replica (gRPC + debug listeners on "
             "ephemeral ports, announced via a rendezvous directory) — "
             "the subprocess half of the real-replica fleet drill")
    p_replica.add_argument("--name", required=True,
                           help="replica name (rendezvous + fleetz row)")
    p_replica.add_argument("--rendezvous", required=True,
                           help="directory to publish <name>.json with "
                                "the resolved addresses into")
    p_replica.add_argument("--grpc-port", type=int, default=0,
                           help="solve wire port (0 = ephemeral)")
    p_replica.add_argument("--debug-port", type=int, default=0,
                           help="metrics/debug listener port (0 = "
                                "ephemeral; the ACTUAL port is published "
                                "through the rendezvous record)")
    p_replica.add_argument("--max-wave", type=int, default=16)
    p_replica.add_argument("--tick-interval", type=float, default=0.01)
    p_replica.add_argument("--starvation-bound", type=int, default=4,
                           help="fairness contract the frontend declares "
                                "(and the drill audits) in ticks; size "
                                "for the offered closed-loop depth")
    p_replica.set_defaults(fn=cmd_fleet_replica)

    p_ctrl = sub.add_parser("controller", help="run the controller plane")
    p_ctrl.add_argument("--simulate", action="store_true",
                        help="use the simulated cloud backend")
    p_ctrl.add_argument("--solver", default="",
                        help="gRPC solver sidecar address (host:port)")
    p_ctrl.add_argument("--cluster-name", default="simulated")
    p_ctrl.add_argument("--state", default="",
                        help="persist the simulated account (instances, "
                             "launch templates) to this JSON file: loaded at "
                             "boot, saved on shutdown — lets `cleanup "
                             "--state` sweep the same account")
    p_ctrl.add_argument("--apply", action="append", default=[],
                        metavar="FILE",
                        help="manifest file(s) to apply at boot "
                             "(reference-compatible Karpenter YAML)")
    p_ctrl.add_argument("--kubeconfig", default="",
                        help="run against a real apiserver (kubeconfig path); "
                             "see karpenter_tpu/fake/apiserver.py for the "
                             "in-repo mini apiserver")
    p_ctrl.add_argument("--leader-elect", action="store_true",
                        help="lease-based leader election (HA replicas)")
    p_ctrl.add_argument("--metrics-port", type=int, default=8080,
                        help="prometheus metrics port (-1 disables serving)")
    p_ctrl.add_argument("--health-port", type=int, default=8081,
                        help="healthz/livez/readyz port (-1 disables)")
    p_ctrl.add_argument("--webhook-port", type=int, default=8443,
                        help="AdmissionReview validating-webhook port "
                             "(-1 disables)")
    p_ctrl.add_argument("--webhook-tls-cert", default="",
                        help="TLS cert for the webhook listener (apiserver "
                             "dials webhooks over TLS; cert-manager mounts it)")
    p_ctrl.add_argument("--webhook-tls-key", default="")
    p_ctrl.set_defaults(fn=cmd_controller)

    p_clean = sub.add_parser(
        "cleanup", help="one-shot sweep of leaked instances/launch templates "
                        "in a persisted simulated account")
    p_clean.add_argument("--state", default="",
                         help="account state file (see controller --state)")
    p_clean.add_argument("--cluster-name", default="simulated")
    p_clean.add_argument("--all", action="store_true",
                         help="ignore grace windows (dead-account sweep)")
    p_clean.add_argument("--launch-templates", action="store_true",
                         help="also delete all cluster-owned launch templates")
    p_clean.set_defaults(fn=cmd_cleanup)

    p_logs = sub.add_parser(
        "logs", help="fetch recent logs from a live controller (/logz)")
    p_logs.add_argument("--endpoint", default="http://127.0.0.1:8081",
                        help="controller health listener base URL")
    p_logs.add_argument("-n", "--lines", type=int, default=500)
    p_logs.add_argument("-f", "--follow", action="store_true",
                        help="poll for new lines")
    p_logs.add_argument("--interval", type=float, default=2.0)
    p_logs.set_defaults(fn=cmd_logs)

    p_statusz = sub.add_parser(
        "statusz", help="pretty-print a live controller's /debug/statusz "
                        "snapshot (introspection plane)")
    p_statusz.add_argument("--endpoint", default="http://127.0.0.1:8080",
                           help="controller metrics listener base URL")
    p_statusz.set_defaults(fn=cmd_statusz)

    p_diag = sub.add_parser(
        "diagnose", help="fetch a diagnostics bundle from a live controller "
                         "(/debug/bundle) — statusz ring + logs + traces + "
                         "events + metrics")
    p_diag.add_argument("--endpoint", default="http://127.0.0.1:8080",
                        help="controller metrics listener base URL")
    p_diag.add_argument("-o", "--out", default="",
                        help="write the bundle to this file (default: stdout)")
    p_diag.set_defaults(fn=cmd_diagnose)

    p_events = sub.add_parser(
        "events", help="fetch recent events from a live controller (/eventz)")
    p_events.add_argument("--endpoint", default="http://127.0.0.1:8081",
                          help="controller health listener base URL")
    p_events.add_argument("-n", "--count", type=int, default=100)
    p_events.add_argument("--json", action="store_true",
                          help="raw JSON instead of columns")
    p_events.set_defaults(fn=cmd_events)

    p_explain = sub.add_parser(
        "explain", help="answer WHY for a pod from a live controller's "
                        "decision-provenance ring (/debug/decisions)")
    p_explain.add_argument("pod", nargs="?", default="",
                           help="pod name to resolve (omit to list the "
                                "decision index)")
    p_explain.add_argument("--id", default="",
                           help="fetch one decision record by id instead")
    p_explain.add_argument("--endpoint", default="http://127.0.0.1:8080",
                           help="controller metrics listener base URL")
    p_explain.add_argument("--limit", type=int, default=20,
                           help="index size when listing")
    p_explain.add_argument("--json", action="store_true",
                           help="raw JSON instead of the human verdict")
    p_explain.set_defaults(fn=cmd_explain)

    p_sync = sub.add_parser(
        "sync", help="apply (and optionally prune to) a manifest fixture "
                     "set against a coordination plane")
    p_sync.add_argument("manifests", nargs="+",
                        help="YAML files or directories")
    p_sync.add_argument("--kubeconfig", required=True,
                        help="target apiserver kubeconfig")
    p_sync.add_argument("--cluster-name", default="simulated")
    p_sync.add_argument("--prune", action="store_true",
                        help="delete managed-kind objects absent from the "
                             "fixture (pods are never pruned)")
    p_sync.set_defaults(fn=cmd_sync)

    p_api = sub.add_parser(
        "apiserver", help="boot the offline mini apiserver + kubeconfig "
                          "(getting-started walkthrough substrate)")
    p_api.add_argument("--port", type=int, default=8001)
    p_api.add_argument("--write-kubeconfig", default="/tmp/karpenter-tpu-kubeconfig")
    p_api.set_defaults(fn=cmd_apiserver)

    p_get = sub.add_parser("get", help="list objects from the coordination "
                                       "plane (kubectl-get analogue)")
    p_get.add_argument("kind", help="nodes, pods, machines, provisioners, ...")
    p_get.add_argument("--kubeconfig", required=True)
    p_get.set_defaults(fn=cmd_get)

    p_chaos = sub.add_parser(
        "chaos", help="seeded deterministic fault-injection sweep with "
                      "cross-layer invariant checks (docs/designs/chaos.md)")
    p_chaos.add_argument("--seed", type=int, default=0,
                         help="plan seed; the same seed replays the identical "
                              "fault sequence and verdict")
    p_chaos.add_argument("--scenarios", type=int, default=1,
                         help="scenarios derived from the seed (0..K-1)")
    p_chaos.add_argument("--intensity", type=float, default=1.0,
                         help="fault-count multiplier per site")
    p_chaos.add_argument("--out-dir", default="benchmarks/results/chaos",
                         help="replay-artifact directory ('' disables)")
    p_chaos.add_argument("--burst", action="store_true",
                         help="run the fixed resilience-plane burst schedule "
                              "(dense cloud-5xx + solver crashes) instead of "
                              "the sampled plan")
    p_chaos.add_argument("--crash", action="store_true",
                         help="run the crash-restart recovery drill: one "
                              "scenario per named crashpoint plus a fenced "
                              "leader-failover scenario "
                              "(docs/designs/recovery.md)")
    p_chaos.add_argument("--storm", action="store_true",
                         help="run the multi-tenant fleet storm drill: a hot "
                              "tenant bursting against light tenants through "
                              "the fleet frontend, asserting the "
                              "fairness-never-starves invariant "
                              "(docs/designs/fleet.md)")
    p_chaos.add_argument("--partition", action="store_true",
                         help="run the fleet membership/failover drill: "
                              "replica kill, blackhole partition, gray "
                              "slow-replica, poison request and rejoin, "
                              "auditing remap blast radius, "
                              "completes-or-sheds, quarantine cascade "
                              "bounds and epoch monotonicity")
    p_chaos.add_argument("--spot-storm", action="store_true",
                         help="spot reclaim-storm drill: 10k-node fleet, "
                              "2000 simultaneous reclaims in one tick, "
                              "forecaster-was-wrong adversarial schedule, "
                              "and the strict-noop decision-parity window")
    p_chaos.add_argument("--spot-nodes", type=int, default=None,
                         help="override the spot-storm fleet size "
                              "(default 10000)")
    p_chaos.add_argument("--spot-reclaims", type=int, default=None,
                         help="override the simultaneous reclaim count "
                              "(default 2000)")
    p_chaos.set_defaults(fn=cmd_chaos)

    p_ver = sub.add_parser("version")
    p_ver.set_defaults(fn=lambda a: print(VERSION) or 0)

    args = parser.parse_args(argv)
    return args.fn(args) or 0


if __name__ == "__main__":
    sys.exit(main())
