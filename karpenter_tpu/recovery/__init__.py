"""Crash-restart recovery plane.

A process death mid-action strands durable state: a cloud instance with no
registered machine, a node marked for deletion only in the dead process's
memory, a consolidation replacement nobody remembers launching. The plane
has three parts:

- **crashpoints** (crashpoints.py): named markers at every in-flight-intent
  site; the chaos crash drill raises `SimulatedCrash` there to prove each
  site recovers.
- **intent journal** (journal.py): write-ahead records persisted through
  the kube store before the first risky step of each action, resolved after
  the last.
- **RecoveryManager** (here): on each incarnation the (re)born leader mints
  a fencing epoch, replays the journal records stranded by PRIOR epochs —
  rolling each action forward or back by inspecting the surviving stores —
  and exposes the whole story to statusz (`recovery` section) and the
  chaos evidence ledger. Replay replaces the 15-minute registration-TTL
  wait with first-cycle resolution.

Fencing rides the same epochs: the leader lease carries one, the store
tracks the highest it has seen, and every leader-gated mutation presents
its epoch (fake/kube.py FencedKube) so a deposed-but-unaware ex-leader's
late writes raise `Fenced` instead of corrupting the successor's state.
"""

from __future__ import annotations

import logging

from ..metrics import REGISTRY
from .crashpoints import (CRASHPOINTS, SimulatedCrash, crashpoint,  # noqa: F401
                          install, uninstall)
from .journal import (JOURNAL_KIND, LAUNCH, REBALANCE,  # noqa: F401
                      RECORD_KINDS, REPLACE, TERMINATION, IntentJournal,
                      IntentRecord)

log = logging.getLogger("karpenter.recovery")

# boot-counter fallback for epoch minting when no leader election is running
# (single-process mode still needs strictly-increasing incarnation epochs so
# replay can tell "stranded by a prior life" from "in flight right now")
BOOT_EPOCH_NAME = "operator-boot-epoch"

REPLAYED_TOTAL = REGISTRY.counter(
    "karpenter_recovery_replayed_total",
    "Stranded intent records replayed on incarnation start, by kind and "
    "resolution.", ("kind", "outcome"))
INCARNATIONS_TOTAL = REGISTRY.counter(
    "karpenter_recovery_incarnations_total",
    "Operator incarnations that began (epoch mints).")
EPOCH_GAUGE = REGISTRY.gauge(
    "karpenter_recovery_epoch",
    "This process's current incarnation/fencing epoch.")


class RecoveryManager:
    """Epoch minting + journal replay for one operator incarnation."""

    # invariant bound: every stranded record must reach a terminal state
    # within this many reconcile cycles of the reborn leader
    REPLAY_BUDGET_CYCLES = 3

    def __init__(self, operator):
        self.op = operator
        self.epoch = 0
        self.replayed: "list[dict]" = []  # replay ledger (statusz/evidence)
        self.last_replay_count = 0

    @property
    def journal(self) -> "IntentJournal":
        return self.op.journal

    # -- incarnation start -----------------------------------------------------

    def begin_incarnation(self) -> int:
        """Mint this life's epoch. Leader-elected processes inherit the
        lease's fencing token (epoch advanced atomically with the leadership
        change); standalone processes persist a boot counter through the
        store. Both consult the store's fence high-water mark so mixed-mode
        histories stay strictly monotone."""
        token = None
        leader = getattr(self.op, "leader", None)
        if leader is not None:
            token = leader.fencing_token()
        if token is not None:
            self.epoch = token
        else:
            store = self.op.kube
            stored = store.get("configmaps", BOOT_EPOCH_NAME)
            if isinstance(stored, dict):
                # HttpKubeStore round-trips configmaps as {"data": {...}}
                stored = stored.get("data", stored)
            prev = stored.get("epoch", 0) if isinstance(stored, dict) else 0
            try:
                prev = int(prev)
            except (TypeError, ValueError):
                prev = 0
            fence = getattr(store, "fence_epoch", None)
            if callable(fence):
                try:
                    prev = max(prev, fence())
                except Exception:
                    pass
            self.epoch = prev + 1
            store.update("configmaps", BOOT_EPOCH_NAME, {"epoch": self.epoch})
        EPOCH_GAUGE.set(self.epoch)
        INCARNATIONS_TOTAL.inc()
        log.info("incarnation epoch %d begins", self.epoch)
        return self.epoch

    # -- replay ----------------------------------------------------------------

    def replay(self) -> "list[dict]":
        """Resolve every record stranded by prior epochs. Run AFTER machine
        hydration (the roll-forward checks read rebuilt cluster state) and
        before normal reconcile cycles. Current-epoch records are skipped —
        they are simply in flight."""
        journal = self.journal
        if journal is None or self.epoch == 0:
            return []
        stale = journal.pending(before_epoch=self.epoch)
        actions: "list[dict]" = []
        for rec in stale:
            try:
                if rec.kind == LAUNCH:
                    outcome = self._replay_launch(rec)
                elif rec.kind == TERMINATION:
                    outcome = self._replay_termination(rec)
                elif rec.kind == REPLACE:
                    outcome = self._replay_replace(rec)
                elif rec.kind == REBALANCE:
                    outcome = self._replay_rebalance(rec)
                else:
                    journal.resolve(rec.kind, rec.key, outcome="unknown_kind")
                    outcome = "unknown_kind"
            except Exception as e:
                log.warning("replay of %s:%s failed: %s", rec.kind, rec.key, e)
                outcome = "error"
            REPLAYED_TOTAL.inc(kind=rec.kind, outcome=outcome)
            actions.append({"kind": rec.kind, "key": rec.key,
                            "epoch": rec.epoch, "outcome": outcome})
            log.info("replayed %s:%s (epoch %d) -> %s",
                     rec.kind, rec.key, rec.epoch, outcome)
        self.replayed.extend(actions)
        self.last_replay_count = len(actions)
        fr = getattr(self.op, "flightrecorder", None)
        if actions and fr is not None:
            fr.trigger("recovery_replay",
                       detail=f"{len(actions)} stranded intent record(s): "
                       + ", ".join(f"{a['kind']}:{a['key']}={a['outcome']}"
                                   for a in actions))
        return actions

    def _replay_launch(self, rec: IntentRecord) -> str:
        """Launch stranded mid-flight. Fully registered (machine has a
        providerID and the kube node exists) rolls FORWARD — the capacity is
        real, and the dead process's unbound pods are still pending, so the
        next provisioning cycle schedules them onto it. Anything less rolls
        BACK: terminate the instance (if one was ever created) and reap the
        half-written kube objects."""
        op = self.op
        machine = op.kube.get("machines", rec.key)
        node_name = (getattr(machine.status, "node_name", "") or rec.key
                     if machine is not None else rec.key)
        registered = (machine is not None
                      and getattr(machine.status, "provider_id", "")
                      and op.kube.get("nodes", node_name) is not None)
        if registered:
            self.journal.resolve(LAUNCH, rec.key, outcome="rolled_forward")
            return "rolled_forward"
        # get_by_machine is tag-scoped and reaps double-launch duplicates
        # itself — exactly-once across restart even if the fleet call and
        # its retry both landed
        inst = None
        try:
            inst = op.cloudprovider.instances.get_by_machine(rec.key)
        except Exception as e:
            log.warning("instance lookup for %s failed: %s", rec.key, e)
        if inst is not None:
            op.cloudprovider.instances.delete(inst.id)
        if machine is not None:
            op.kube.delete("machines", rec.key)
        if node_name in op.cluster.nodes:
            op.cluster.delete_node(node_name)
        op.kube.delete("nodes", node_name)
        self.journal.resolve(LAUNCH, rec.key, outcome="rolled_back")
        return "rolled_back"

    def _replay_termination(self, rec: IntentRecord) -> str:
        """Termination stranded mid-teardown. A node still live in cluster
        state re-enters the normal flow (request_deletion re-establishes the
        in-memory mark AND refreshes the record under the current epoch — no
        resolve here, the ordinary path resolves it). Dead capacity with
        leftover kube objects is reaped directly; nothing left is done."""
        op = self.op
        machine_name = str(rec.payload.get("machine") or "")
        node_kube = op.kube.get("nodes", rec.key)
        machine = (op.kube.get("machines", machine_name)
                   if machine_name else None)
        if op.cluster.nodes.get(rec.key) is not None:
            if op.termination.request_deletion(rec.key):
                return "requeued"
        if node_kube is None and machine is None:
            self.journal.resolve(TERMINATION, rec.key, outcome="already_done")
            return "already_done"
        if machine is not None:
            op.kube.delete("machines", machine_name)
        if node_kube is not None:
            op.kube.delete("nodes", rec.key)
        self.journal.resolve(TERMINATION, rec.key, outcome="reaped")
        return "reaped"

    def _replay_replace(self, rec: IntentRecord) -> str:
        """Two-phase replace stranded after the replacement launch. The
        in-memory state machine died; if workload already rebound onto the
        replacement keep it (the old nodes fall to normal consolidation),
        otherwise roll the empty replacement back."""
        op = self.op
        rep_name = rec.payload.get("replacement")
        rep = op.cluster.nodes.get(rep_name) if rep_name else None
        if rep is None:
            outcome = "already_done" if rep_name else "aborted"
        elif rep.non_daemon_pods():
            outcome = "rolled_forward"
        else:
            op.termination.request_deletion(rep_name)
            outcome = "rolled_back"
        self.journal.resolve(REPLACE, rec.key, outcome=outcome)
        return outcome

    def _replay_rebalance(self, rec: IntentRecord) -> str:
        """Proactive spot rebalance stranded mid-flight (spot/rebalance.py
        two-phase). The drain only ever fires AFTER the replacement
        initializes, so the stranded states mirror replace: workload
        already on the replacement keeps it (roll forward), an empty
        replacement is reaped (roll back), a never-launched one is just
        resolved. The original at-risk node was never touched — reactive
        interruption handling still covers it either way."""
        op = self.op
        rep_name = rec.payload.get("replacement")
        rep = op.cluster.nodes.get(rep_name) if rep_name else None
        if rep is None:
            outcome = "already_done" if rep_name else "aborted"
        elif rep.non_daemon_pods():
            outcome = "rolled_forward"
        else:
            op.termination.request_deletion(rep_name)
            outcome = "rolled_back"
        self.journal.resolve(REBALANCE, rec.key, outcome=outcome)
        return outcome

    # -- introspection ---------------------------------------------------------

    def snapshot(self) -> dict:
        """The statusz `recovery` section (schema v3)."""
        out = {"epoch": self.epoch,
               "replayed_total": len(self.replayed),
               "last_replay": list(self.replayed[-8:])}
        journal = self.journal
        if journal is not None:
            out["journal"] = journal.snapshot()
        store = self.op.kube
        fence = getattr(store, "fence_epoch", None)
        if callable(fence):
            try:
                out["fence_epoch"] = fence()
            except Exception:
                pass
        rejected = getattr(store, "fenced_writes_rejected", None)
        if isinstance(rejected, int):
            out["fenced_writes_rejected"] = rejected
        interruption = getattr(self.op, "interruption", None)
        if interruption is not None:
            out["interruption_deduped"] = interruption.deduped_count
        return out
