"""Durable intent journal: write-ahead records for in-flight actions.

Every action whose partial completion would strand cloud or kube state
(a fleet launch, a node termination, a consolidation replace) writes an
IntentRecord through the kube store BEFORE acting and resolves it after
the last step. The record survives the process: a reborn leader replays
unresolved records from prior epochs on its first cycles
(recovery.RecoveryManager) instead of waiting out the 15-minute
registration-TTL sweep.

Records are plain kube objects (KubeStore kind "intents"), so they ride
the same durability, fencing, and watch semantics as every other object —
and a real deployment can back them with CRDs or a ConfigMap without
changing the journal surface.
"""

from __future__ import annotations

import dataclasses
from typing import Callable, Optional

from ..fake.kube import Conflict
from ..metrics import REGISTRY
from ..utils.clock import Clock

JOURNAL_KIND = "intents"

# record kinds
LAUNCH = "launch"            # fleet launch in flight (machine name keys it)
TERMINATION = "termination"  # node marked for deletion, teardown in flight
REPLACE = "replace"          # consolidation replace action in flight
REBALANCE = "rebalance"      # proactive spot rebalance in flight
RECORD_KINDS = (LAUNCH, TERMINATION, REPLACE, REBALANCE)

RECORDS_TOTAL = REGISTRY.counter(
    "karpenter_recovery_journal_records_total",
    "Write-ahead intent records written, by kind.", ("kind",))
RESOLVED_TOTAL = REGISTRY.counter(
    "karpenter_recovery_journal_resolved_total",
    "Intent records resolved, by kind and outcome.", ("kind", "outcome"))
PENDING_GAUGE = REGISTRY.gauge(
    "karpenter_recovery_journal_pending",
    "Unresolved intent records currently in the journal, by kind.",
    ("kind",))


@dataclasses.dataclass
class IntentRecord:
    kind: str        # one of RECORD_KINDS
    key: str         # unique within kind (machine name, node name, action id)
    payload: dict    # everything replay needs; JSON-serializable values only
    epoch: int = 0   # writer's incarnation epoch (replay targets older ones)
    created_ts: float = 0.0

    @property
    def name(self) -> str:
        return f"{self.kind}:{self.key}"

    def as_dict(self) -> dict:
        return {"kind": self.kind, "key": self.key,
                "payload": dict(self.payload), "epoch": self.epoch,
                "created_ts": self.created_ts}


class IntentJournal:
    """Record/resolve surface over the kube store's "intents" kind."""

    def __init__(self, kube, clock: "Optional[Clock]" = None,
                 epoch_fn: "Optional[Callable[[], int]]" = None):
        self.kube = kube
        self.clock = clock or Clock()
        self._epoch_fn = epoch_fn or (lambda: 0)

    def record(self, kind: str, key: str, payload: dict) -> IntentRecord:
        """Write-ahead: persist the intent BEFORE the first risky step.
        Re-recording an existing key refreshes it under the current epoch
        (a replayed intent re-entering the normal flow)."""
        rec = IntentRecord(kind=kind, key=key, payload=dict(payload),
                           epoch=self._epoch_fn(),
                           created_ts=self.clock.now())
        try:
            self.kube.create(JOURNAL_KIND, rec.name, rec)
        except Conflict:
            self.kube.update(JOURNAL_KIND, rec.name, rec)
        RECORDS_TOTAL.inc(kind=kind)
        self._refresh_gauge()
        return rec

    def resolve(self, kind: str, key: str, outcome: str = "completed") -> bool:
        """The action reached a terminal state; drop the record."""
        gone = self.kube.delete(JOURNAL_KIND, f"{kind}:{key}") is not None
        if gone:
            RESOLVED_TOTAL.inc(kind=kind, outcome=outcome)
        self._refresh_gauge()
        return gone

    def get(self, kind: str, key: str) -> "Optional[IntentRecord]":
        return self.kube.get(JOURNAL_KIND, f"{kind}:{key}")

    def pending(self, kind: "Optional[str]" = None,
                before_epoch: "Optional[int]" = None) -> "list[IntentRecord]":
        """Unresolved records, oldest first. `before_epoch` restricts to
        records stranded by earlier incarnations (what replay targets —
        the current epoch's records are simply in flight)."""
        out = [r for r in self.kube.list(JOURNAL_KIND)
               if (kind is None or r.kind == kind)
               and (before_epoch is None or r.epoch < before_epoch)]
        out.sort(key=lambda r: (r.created_ts, r.name))
        return out

    def snapshot(self) -> dict:
        by_kind: "dict[str, int]" = {}
        for r in self.kube.list(JOURNAL_KIND):
            by_kind[r.kind] = by_kind.get(r.kind, 0) + 1
        return {"pending": sum(by_kind.values()),
                "pending_by_kind": dict(sorted(by_kind.items()))}

    def _refresh_gauge(self) -> None:
        counts = {k: 0 for k in RECORD_KINDS}
        try:
            for r in self.kube.list(JOURNAL_KIND):
                counts[r.kind] = counts.get(r.kind, 0) + 1
        except Exception:
            return
        for k, v in counts.items():
            PENDING_GAUGE.set(v, kind=k)
