"""Named crashpoints: every in-flight-intent site declares where a process
death would strand durable state.

A crashpoint is a zero-cost marker (`crashpoint("launch.pre_register")`)
placed at each point where the controller has written a write-ahead intent
record (recovery/journal.py) but not yet resolved it. The chaos crash drill
installs a hook that raises `SimulatedCrash` at a scheduled site, which
unwinds the drive stack WITHOUT running the `except Exception` cleanup
fences (SimulatedCrash derives from BaseException precisely so in-band
cleanup cannot tidy up state a real SIGKILL would have left behind); the
runner then tears down the operator object graph and boots a fresh one
against the surviving stores.

`CRASHPOINTS` is the canonical catalog — hack/check_crashpoints.py asserts
every `crashpoint(...)` call site uses a catalogued name and every
catalogued name has exactly one call site, and that every file writing
journal records declares at least one crashpoint.
"""

from __future__ import annotations

import threading
from typing import Callable, Optional

# site -> where it lives; ordering is the drill order
CRASHPOINTS: "tuple[str, ...]" = (
    # post-token-claim / pre-dispatch: the launch intent is journaled and
    # the machine object exists, but the CreateFleet call has not left the
    # batcher yet
    "fleet.pre_dispatch",
    # the cloud instance exists but the machine's providerID/status was
    # never written back (the classic leak the registration-TTL sweep
    # used to wait 15 minutes for)
    "launch.pre_register",
    # node + machine registered, some of the assigned pods bound
    "launch.mid_bind",
    # cloud capacity already terminated, kube machine/node objects remain
    "termination.mid_delete",
    # consolidation replacement launched, old nodes not yet marked
    "deprovisioning.mid_replace",
    # interruption message handled and recorded, but not yet acked —
    # redelivery lands on the reborn consumer
    "interruption.pre_ack",
    # proactive spot rebalance: replacement launched and journaled, the
    # at-risk node not yet drained (spot/rebalance.py two-phase)
    "spot.mid_rebalance",
)


class SimulatedCrash(BaseException):
    """Process death at a crashpoint. BaseException on purpose: the
    `except Exception` fences that tidy up after *recoverable* errors must
    not see this — a real crash gives them no chance to run either."""

    def __init__(self, site: str):
        super().__init__(f"simulated crash at {site}")
        self.site = site


_lock = threading.Lock()
_hook: "Optional[Callable[[str], None]]" = None


def install(hook: "Callable[[str], None]") -> None:
    """Install the process-wide crash hook (chaos drill only)."""
    global _hook
    with _lock:
        _hook = hook


def uninstall() -> None:
    global _hook
    with _lock:
        _hook = None


def crashpoint(site: str) -> None:
    """Marker at an in-flight-intent site. No-op unless a drill hook is
    installed; the hook may raise SimulatedCrash."""
    hook = _hook
    if hook is not None:
        hook(site)
