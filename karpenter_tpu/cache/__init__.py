"""TTL caches and the unavailable-offerings (ICE) cache.

Parity targets:
- TTL constants — /root/reference/pkg/cache/cache.go:19-37 (DefaultTTL=1m,
  UnavailableOfferingsTTL=3m, InstanceTypesAndZonesTTL=5m).
- `UnavailableOfferings` keyed `capacityType:instanceType:zone` with an atomic
  SeqNum bumped on writes so downstream memoization keys invalidate instantly
  ("retry in milliseconds instead of minutes") —
  /root/reference/pkg/cache/unavailableofferings.go:31-80.
"""

from __future__ import annotations

import threading
from typing import Any, Callable, Iterable, Optional

from ..utils.clock import Clock

DEFAULT_TTL = 60.0
UNAVAILABLE_OFFERINGS_TTL = 180.0
INSTANCE_TYPES_AND_ZONES_TTL = 300.0
PRICING_REFRESH_PERIOD = 12 * 3600.0


_MISSING = object()


class TTLCache:
    """Thread-safe TTL cache with injectable clock (go-cache analogue)."""

    def __init__(self, ttl: float = DEFAULT_TTL, clock: Optional[Clock] = None):
        self.ttl = ttl
        self.clock = clock or Clock()
        self._data: "dict[Any, tuple[float, Any]]" = {}
        self._lock = threading.Lock()

    def lookup(self, key) -> "tuple[bool, Any]":
        """(found, value) — distinguishes a cached None from a miss."""
        with self._lock:
            hit = self._data.get(key, _MISSING)
            if hit is _MISSING:
                return False, None
            expiry, value = hit
            if self.clock.now() >= expiry:
                del self._data[key]
                return False, None
            return True, value

    def get(self, key) -> Optional[Any]:
        return self.lookup(key)[1]

    def set(self, key, value, ttl: Optional[float] = None) -> None:
        with self._lock:
            self._data[key] = (self.clock.now() + (ttl if ttl is not None else self.ttl), value)

    def delete(self, key) -> None:
        with self._lock:
            self._data.pop(key, None)

    def get_or_load(self, key, loader: Callable[[], Any], ttl: Optional[float] = None):
        found, hit = self.lookup(key)
        if found:
            return hit
        value = loader()
        self.set(key, value, ttl)
        return value

    def flush(self) -> None:
        with self._lock:
            self._data.clear()

    def keys(self) -> "list":
        now = self.clock.now()
        with self._lock:
            return [k for k, (exp, _) in self._data.items() if now < exp]


class UnavailableOfferings:
    """ICE-aware offering blocklist with seqnum invalidation
    (unavailableofferings.go:31-80)."""

    def __init__(self, clock: Optional[Clock] = None, ttl: float = UNAVAILABLE_OFFERINGS_TTL):
        self._cache = TTLCache(ttl=ttl, clock=clock)
        self._seqnum = 0
        self._lock = threading.Lock()

    @property
    def seqnum(self) -> int:
        with self._lock:
            return self._seqnum

    @staticmethod
    def _key(capacity_type: str, instance_type: str, zone: str) -> str:
        return f"{capacity_type}:{instance_type}:{zone}"

    def is_unavailable(self, capacity_type: str, instance_type: str, zone: str) -> bool:
        return self._cache.get(self._key(capacity_type, instance_type, zone)) is not None

    def mark_unavailable(self, reason: str, instance_type: str, zone: str,
                         capacity_type: str) -> None:
        self._cache.set(self._key(capacity_type, instance_type, zone), reason)
        with self._lock:
            self._seqnum += 1

    def mark_unavailable_for_fleet_err(self, err, capacity_type: str) -> None:
        """Fleet launch error -> poison every (type, zone) it names
        (instance.go:419-425 MarkUnavailableForFleetErr)."""
        for instance_type, zone in getattr(err, "failed_pools", []):
            self.mark_unavailable(getattr(err, "code", "FleetError"),
                                  instance_type, zone, capacity_type)

    def delete(self, capacity_type: str, instance_type: str, zone: str) -> None:
        self._cache.delete(self._key(capacity_type, instance_type, zone))
        with self._lock:
            self._seqnum += 1

    def flush(self) -> None:
        self._cache.flush()
        with self._lock:
            self._seqnum += 1

    def apply(self, catalog_types: Iterable) -> "list":
        """Project availability onto instance types: offerings present in this
        cache flip available=False (createOfferings parity,
        instancetypes.go:133-161)."""
        import dataclasses

        from ..models.instancetype import Offerings

        out = []
        for t in catalog_types:
            offs = []
            dirty = False
            for o in t.offerings:
                if o.available and self.is_unavailable(o.capacity_type, t.name, o.zone):
                    offs.append(dataclasses.replace(o, available=False))
                    dirty = True
                else:
                    offs.append(o)
            out.append(dataclasses.replace(t, offerings=Offerings(offs)) if dirty else t)
        return out
