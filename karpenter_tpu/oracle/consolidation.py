"""Scalar reference consolidation search — the exact-semantics spec.

Parity target: /root/reference/designs/consolidation.md:
- Node Deletion: all of a node's evictable pods re-schedule onto the rest of
  the cluster -> delete; savings = node price.
- Node Replacement: pods fit on (cluster - node) plus ONE strictly-cheaper new
  node -> replace; savings = price delta.
- Single-node changes only; candidates scored by disruption cost =
  f(#pods, pod-deletion-cost, priority) weighted by lifetime remaining
  (1.0 at creation -> 0.0 at ttlSecondsUntilExpired).
- Pods that prevent consolidation: do-not-evict, bare pods, PDB exhausted.

The TPU kernel (karpenter_tpu/ops/consolidate.py) evaluates ALL candidates in
one batched solve and is differential-tested against this module.
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Sequence

from ..apis import wellknown as wk
from ..apis.provisioner import Provisioner
from ..models.cluster import ClusterState, StateNode, pod_evictable
from ..models.instancetype import Catalog
from ..oracle.scheduler import Scheduler

# price must improve by a margin to bother replacing (avoids churn on noise)
REPLACE_PRICE_EPS = 1e-9


@dataclasses.dataclass
class ConsolidationAction:
    kind: str  # "delete" | "replace"
    node: str
    disruption_cost: float
    savings: float
    replacement: Optional[tuple] = None  # (instance type, zone, capacityType, price)

    def sort_key(self):
        return (self.disruption_cost, -self.savings, self.node)


def lifetime_factor(node: StateNode, prov: Optional[Provisioner], now: float) -> float:
    """1.0 at creation, linear to 0.0 at expiry (consolidation.md 'Node Age')."""
    if prov is None or prov.ttl_seconds_until_expired is None:
        return 1.0
    ttl = prov.ttl_seconds_until_expired
    if ttl <= 0:
        return 0.0
    age = max(0.0, now - node.created_ts)
    return max(0.0, min(1.0, 1.0 - age / ttl))


def disruption_cost(node: StateNode, prov: Optional[Provisioner], now: float) -> float:
    """Blend of pod count, deletion cost and priority, scaled by lifetime
    remaining (consolidation.md scoring)."""
    cost = 0.0
    for p in node.non_daemon_pods():
        cost += 1.0 + max(p.deletion_cost, 0) / 1000.0 + max(p.priority, 0) / 1e6
    return cost * lifetime_factor(node, prov, now)


def eligible(node: StateNode, cluster: ClusterState) -> bool:
    if node.marked_for_deletion or not node.initialized:
        return False
    if node.is_empty():
        return False  # emptiness path handles these (cheaper than simulation)
    healthy = {
        pdb.name: sum(1 for n in cluster.nodes.values() for p in n.pods if pdb.matches(p))
        for pdb in cluster.pdbs
    }
    pods = node.non_daemon_pods()
    if not all(pod_evictable(p, cluster.pdbs, healthy) for p in pods):
        return False
    # aggregate check: deleting the node evicts ALL its matching pods at once,
    # so the per-PDB headroom must cover the node's whole matching set
    for pdb in cluster.pdbs:
        on_node = sum(1 for p in pods if pdb.matches(p))
        if on_node and pdb.disruptions_allowed(healthy.get(pdb.name, 0)) < on_node:
            return False
    return True


def evaluate_candidate(
    node: StateNode,
    cluster: ClusterState,
    catalog: Catalog,
    provisioners: Sequence[Provisioner],
    daemon_overhead: Optional[Sequence[int]] = None,
    now: float = 0.0,
) -> Optional[ConsolidationAction]:
    """Simulated scheduling of `node`'s pods against the rest of the cluster,
    with at most one strictly-cheaper replacement node."""
    others = cluster.existing_views(exclude={node.name})
    pods = node.non_daemon_pods()
    # restrict the replacement universe to OPTIONS strictly cheaper than the
    # node (option-level filter — the kernel applies the identical per-option
    # cheaper mask over the full grid, so both paths share one universe)
    cheaper_types = []
    for t in catalog.types:
        offs = type(t.offerings)(
            o for o in t.offerings
            if o.available and o.price < node.price - REPLACE_PRICE_EPS)
        if offs:
            cheaper_types.append(dataclasses.replace(t, offerings=offs))
    cheaper = Catalog(types=cheaper_types, seqnum=catalog.seqnum)
    sched = Scheduler(cheaper, provisioners, daemon_overhead)
    res = sched.schedule(list(pods), existing=others)
    if res.unschedulable or len(res.new_nodes) > 1:
        return None
    prov = next((p for p in provisioners if p.name == node.provisioner_name), None)
    cost = disruption_cost(node, prov, now)
    if not res.new_nodes:
        return ConsolidationAction("delete", node.name, cost, savings=node.price)
    claim = res.new_nodes[0]
    opt = claim.decided
    if opt.price >= node.price - REPLACE_PRICE_EPS:
        return None
    return ConsolidationAction(
        "replace", node.name, cost, savings=node.price - opt.price,
        replacement=(opt.itype.name, opt.zone, opt.capacity_type, opt.price),
    )


def find_consolidation(
    cluster: ClusterState,
    catalog: Catalog,
    provisioners: Sequence[Provisioner],
    daemon_overhead: Optional[Sequence[int]] = None,
    now: float = 0.0,
) -> Optional[ConsolidationAction]:
    """Best single-node action, min disruption cost first (consolidation.md
    'Selecting Nodes for Consolidation')."""
    actions = []
    for name in sorted(cluster.nodes):
        node = cluster.nodes[name]
        if not eligible(node, cluster):
            continue
        act = evaluate_candidate(node, cluster, catalog, provisioners,
                                 daemon_overhead, now)
        if act is not None:
            actions.append(act)
    if not actions:
        return None
    return min(actions, key=ConsolidationAction.sort_key)
