"""Scalar reference consolidation search — the exact-semantics spec.

Parity target: /root/reference/designs/consolidation.md:
- Node Deletion: all of a node's evictable pods re-schedule onto the rest of
  the cluster -> delete; savings = node price.
- Node Replacement: pods fit on (cluster - node) plus ONE strictly-cheaper new
  node -> replace; savings = price delta.
- Single-node changes only; candidates scored by disruption cost =
  f(#pods, pod-deletion-cost, priority) weighted by lifetime remaining
  (1.0 at creation -> 0.0 at ttlSecondsUntilExpired).
- Pods that prevent consolidation: do-not-evict, bare pods, PDB exhausted.

The TPU kernel (karpenter_tpu/ops/consolidate.py) evaluates ALL candidates in
one batched solve and is differential-tested against this module.
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Sequence

from ..apis import wellknown as wk
from ..apis.provisioner import Provisioner
from ..models.cluster import (ANNOTATION_DO_NOT_CONSOLIDATE, ClusterState,
                              StateNode, pod_evictable)
from ..models.instancetype import Catalog
from ..oracle.scheduler import Scheduler

# price must improve by a margin to bother replacing (avoids churn on noise)
REPLACE_PRICE_EPS = 1e-9


@dataclasses.dataclass
class ConsolidationAction:
    kind: str  # "delete" | "replace"
    node: str  # primary node (nodes[0])
    disruption_cost: float
    savings: float
    replacement: Optional[tuple] = None  # (instance type, zone, capacityType, price)
    # all nodes the action disrupts; multi-node actions (the TPU headroom
    # feature the Go reference skips for cost, consolidation.md 'Selecting
    # Nodes') carry >1 entry
    nodes: "tuple[str, ...]" = ()

    def __post_init__(self):
        if not self.nodes:
            self.nodes = (self.node,)

    def sort_key(self):
        return (self.disruption_cost, -self.savings, self.nodes)


def lifetime_factor(node: StateNode, prov: Optional[Provisioner], now: float) -> float:
    """1.0 at creation, linear to 0.0 at expiry (consolidation.md 'Node Age')."""
    if prov is None or prov.ttl_seconds_until_expired is None:
        return 1.0
    ttl = prov.ttl_seconds_until_expired
    if ttl <= 0:
        return 0.0
    age = max(0.0, now - node.created_ts)
    return max(0.0, min(1.0, 1.0 - age / ttl))


def disruption_cost(node: StateNode, prov: Optional[Provisioner], now: float) -> float:
    """Blend of pod count, deletion cost and priority, scaled by lifetime
    remaining (consolidation.md scoring)."""
    cost = 0.0
    for p in node.non_daemon_pods():
        cost += 1.0 + max(p.deletion_cost, 0) / 1000.0 + max(p.priority, 0) / 1e6
    return cost * lifetime_factor(node, prov, now)


def eligible(node: StateNode, cluster: ClusterState) -> bool:
    if node.marked_for_deletion or not node.initialized:
        return False
    if node.annotations.get(ANNOTATION_DO_NOT_CONSOLIDATE) == "true":
        return False  # node-level veto (reference deprovisioning.md)
    if node.is_empty():
        return False  # emptiness path handles these (cheaper than simulation)
    if cluster.nodes.get(node.name) is node:
        # cluster-owned node: the cached columnar verdict, recomputed only
        # when the node's row or the PDB set changed since the last call
        # (parity with the scalar sweep below is property-tested)
        return cluster.node_consolidation_clear(node)
    healthy = {
        pdb.name: sum(1 for n in cluster.nodes.values() for p in n.pods if pdb.matches(p))
        for pdb in cluster.pdbs
    }
    pods = node.non_daemon_pods()
    if not all(pod_evictable(p, cluster.pdbs, healthy) for p in pods):
        return False
    # aggregate check: deleting the node evicts ALL its matching pods at once,
    # so the per-PDB headroom must cover the node's whole matching set
    for pdb in cluster.pdbs:
        on_node = sum(1 for p in pods if pdb.matches(p))
        if on_node and pdb.disruptions_allowed(healthy.get(pdb.name, 0)) < on_node:
            return False
    return True


def evaluate_candidate_set(
    nodes: "Sequence[StateNode]",
    cluster: ClusterState,
    catalog: Catalog,
    provisioners: Sequence[Provisioner],
    daemon_overhead: Optional[Sequence[int]] = None,
    now: float = 0.0,
) -> Optional[ConsolidationAction]:
    """Simulated scheduling of the set's combined pods against the rest of
    the cluster, with at most one replacement strictly cheaper than the set's
    combined price. |nodes| == 1 is the reference's single-node search;
    |nodes| > 1 is the multi-node search the Go reference skips for cost."""
    names = {n.name for n in nodes}
    total_price = sum(n.price for n in nodes)
    others = cluster.existing_views(exclude=names)
    pods = [p for n in nodes for p in n.non_daemon_pods()]
    # restrict the replacement universe to OPTIONS strictly cheaper than the
    # set (option-level filter — the kernel applies the identical per-option
    # cheaper mask over the full grid, so both paths share one universe)
    cheaper_types = []
    for t in catalog.types:
        offs = type(t.offerings)(
            o for o in t.offerings
            if o.available and o.price < total_price - REPLACE_PRICE_EPS)
        if offs:
            cheaper_types.append(dataclasses.replace(t, offerings=offs))
    cheaper = Catalog(types=cheaper_types, seqnum=catalog.seqnum)
    sched = Scheduler(cheaper, provisioners, daemon_overhead)
    res = sched.schedule(list(pods), existing=others)
    if res.unschedulable or len(res.new_nodes) > 1:
        return None
    cost = sum(
        disruption_cost(
            n, next((p for p in provisioners if p.name == n.provisioner_name),
                    None), now)
        for n in nodes)
    ordered = tuple(sorted(names))
    if not res.new_nodes:
        return ConsolidationAction("delete", ordered[0], cost,
                                   savings=total_price, nodes=ordered)
    if any(n.capacity_type == wk.CAPACITY_TYPE_SPOT for n in nodes):
        # spot nodes consolidate by DELETION only: replacing with the
        # now-cheapest offering would defeat capacity-optimized spot
        # selection and raise interruption rates (reference website
        # deprovisioning.md:88 "It will not replace a spot node with a
        # cheaper spot node"). Gating the outcome (not the universe)
        # keeps the simulation identical to the non-spot path, so a
        # delete verdict means the same thing either way.
        return None
    claim = res.new_nodes[0]
    opt = claim.decided
    if opt.price >= total_price - REPLACE_PRICE_EPS:
        return None
    return ConsolidationAction(
        "replace", ordered[0], cost, savings=total_price - opt.price,
        replacement=(opt.itype.name, opt.zone, opt.capacity_type, opt.price),
        nodes=ordered,
    )


def evaluate_candidate(
    node: StateNode,
    cluster: ClusterState,
    catalog: Catalog,
    provisioners: Sequence[Provisioner],
    daemon_overhead: Optional[Sequence[int]] = None,
    now: float = 0.0,
) -> Optional[ConsolidationAction]:
    return evaluate_candidate_set([node], cluster, catalog, provisioners,
                                  daemon_overhead, now)


def find_consolidation(
    cluster: ClusterState,
    catalog: Catalog,
    provisioners: Sequence[Provisioner],
    daemon_overhead: Optional[Sequence[int]] = None,
    now: float = 0.0,
    candidate_filter=None,
    nodes: "Optional[Sequence[StateNode]]" = None,
) -> Optional[ConsolidationAction]:
    """Best single-node action, min disruption cost first (consolidation.md
    'Selecting Nodes for Consolidation'). `candidate_filter` restricts which
    nodes may be candidates (e.g. consolidation-enabled provisioners only);
    all nodes still host rescheduled pods. Pass `nodes` to reuse an
    eligibility sweep already done (the controller's dirty-driven list)."""
    if nodes is None:
        nodes = (cluster.nodes[name] for name in sorted(cluster.nodes)
                 if eligible(cluster.nodes[name], cluster))
    actions = []
    for node in nodes:
        if candidate_filter is not None and not candidate_filter(node):
            continue
        act = evaluate_candidate(node, cluster, catalog, provisioners,
                                 daemon_overhead, now)
        if act is not None:
            actions.append(act)
    if not actions:
        return None
    return min(actions, key=ConsolidationAction.sort_key)


MAX_PAIR_CANDIDATES = 32  # pair search over the M cheapest-to-disrupt nodes


def _pair_pdb_safe(a: StateNode, b: StateNode, cluster: ClusterState) -> bool:
    """The aggregate PDB-headroom invariant for SIMULTANEOUS eviction of both
    nodes: eligible() checks each node's matching set alone; a pair evicts
    the union at once, so the combined set must fit the budget too."""
    if not cluster.pdbs:
        return True
    if cluster.nodes.get(a.name) is a and cluster.nodes.get(b.name) is b:
        # cluster-owned pair: merged per-PDB counts off the cached per-node
        # evictability maps (same aggregate check, no full pod sweep)
        return cluster.pair_pdb_clear(a, b)
    healthy = {
        pdb.name: sum(1 for n in cluster.nodes.values()
                      for p in n.pods if pdb.matches(p))
        for pdb in cluster.pdbs
    }
    pods = a.non_daemon_pods() + b.non_daemon_pods()
    for pdb in cluster.pdbs:
        on_pair = sum(1 for p in pods if pdb.matches(p))
        if on_pair and pdb.disruptions_allowed(healthy.get(pdb.name, 0)) < on_pair:
            return False
    return True


def candidate_pairs(cluster: ClusterState, provisioners, now: float,
                    max_candidates: int = MAX_PAIR_CANDIDATES,
                    nodes: "Optional[Sequence[StateNode]]" = None,
                    candidate_filter=None):
    """Eligible nodes pruned to the cheapest-to-disrupt M, paired; pairs
    violating the combined PDB budget are dropped. Pass `nodes` to reuse an
    eligibility sweep already done (the kernel path reuses its singles
    batch)."""
    if nodes is None:
        nodes = [cluster.nodes[name] for name in sorted(cluster.nodes)
                 if eligible(cluster.nodes[name], cluster)]
    if candidate_filter is not None:
        nodes = [n for n in nodes if candidate_filter(n)]
    scored = sorted(
        (disruption_cost(
            n, next((p for p in provisioners
                     if p.name == n.provisioner_name), None), now),
         n.name, n)
        for n in nodes)
    pruned = [n for _, _, n in scored[:max_candidates]]
    return [(pruned[i], pruned[j])
            for i in range(len(pruned)) for j in range(i + 1, len(pruned))
            if _pair_pdb_safe(pruned[i], pruned[j], cluster)]


def find_multi_consolidation(
    cluster: ClusterState,
    catalog: Catalog,
    provisioners: Sequence[Provisioner],
    daemon_overhead: Optional[Sequence[int]] = None,
    now: float = 0.0,
    max_candidates: int = MAX_PAIR_CANDIDATES,
    candidate_filter=None,
    nodes: "Optional[Sequence[StateNode]]" = None,
) -> Optional[ConsolidationAction]:
    """Best two-node action — mechanism 2 of consolidation, which the
    reference runs BEFORE the single-node search (deprovisioning.md:74-77
    at v0.24.0): a multi-node win shadows a single-node one.
    NOTE: sequential simulation is O(pairs) scheduler runs;
    callers without the batched kernel should cap max_candidates hard (the
    controller's oracle fallback uses 8 -> <=28 simulations)."""
    actions = []
    for pair in candidate_pairs(cluster, provisioners, now, max_candidates,
                                nodes=nodes,
                                candidate_filter=candidate_filter):
        act = evaluate_candidate_set(pair, cluster, catalog, provisioners,
                                     daemon_overhead, now)
        if act is not None:
            actions.append(act)
    if not actions:
        return None
    return min(actions, key=ConsolidationAction.sort_key)
