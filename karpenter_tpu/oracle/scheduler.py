"""Scalar reference scheduler — the exact-semantics spec (the "Go fallback").

Parity target: karpenter-core's provisioning scheduler, specified by
/root/reference/designs/bin-packing.md:17-43 (First-Fit-Decreasing: sort pods
by non-increasing requests; pods go to the first node that fits; new nodes
keep the full set of instance types that can satisfy the accumulated pods) and
the selection semantics of /root/reference/pkg/cloudprovider/instance.go:430-462
(price-ordered choice; spot taken when allowed and offered).

Semantics model (shared letter-for-letter with the TPU kernel in
karpenter_tpu/ops/packer.py):

* The schedulable universe is a list of OPTIONS — one per (instanceType, zone,
  capacityType) offering. Every label constraint a pod or provisioner can
  express either (a) is determined by the option (type labels, zone,
  capacity-type, provisioner labels) or (b) is a fixed per-pod-vs-provisioner
  check. Hence a node-under-construction is fully described by its surviving
  option set + used-resource vector — the reference's "requirements tighten as
  pods are added" behavior falls out of option-set intersection.

* FFD: pods sorted by (cpu desc, memory desc, name asc). Each pod lands on the
  FIRST open node (creation order) whose option set intersects the pod's and
  whose capacity still fits; otherwise a new node is opened for the
  highest-weight provisioner that admits the pod.

* Final launch decision per node: cheapest available option; ties broken by
  (price, spot-before-on-demand, type name, zone) — mirroring CreateFleet
  lowest-price / price-capacity-optimized selection (instance.go:240-244).

This oracle is used (1) as the in-process fallback solver when the TPU sidecar
is unreachable (BASELINE.json north star) and (2) as the golden model the
kernel is differential-tested against.
"""

from __future__ import annotations

import dataclasses
from typing import Iterable, Optional, Sequence

from ..apis import wellknown as wk
from ..apis.provisioner import Provisioner
from ..models.instancetype import Catalog, InstanceType
from ..models.pod import PodGroup, PodSpec, Taint, group_pods, tolerates_all
from ..models.requirements import Requirement, Requirements, IncompatibleError, OP_IN


@dataclasses.dataclass(frozen=True)
class Option:
    """One schedulable (type, zone, capacityType) offering."""

    index: int
    itype: InstanceType
    zone: str
    capacity_type: str
    price: float
    alloc: "tuple[int, ...]" = ()  # precomputed allocatable_vector (hot-loop cache)

    def sort_key(self):
        # price asc; spot preferred at equal price (instance.go:430-443 takes
        # spot whenever allowed+offered; spot is cheaper in practice, this tie
        # break makes that deterministic at equal price); then name, zone.
        return (self.price, self.capacity_type != wk.CAPACITY_TYPE_SPOT, self.itype.name, self.zone)


def build_options(catalog: Catalog) -> "list[Option]":
    opts: "list[Option]" = []
    for t in catalog.types:
        alloc = tuple(t.allocatable_vector())
        for o in t.offerings:
            if not o.available:
                continue
            opts.append(Option(len(opts), t, o.zone, o.capacity_type, o.price, alloc))
    return opts


DEFAULT_EVICTION_HARD = 100 * 2**20  # KubeletConfiguration default


def kubelet_is_default(k) -> bool:
    return (k.max_pods is None and k.pods_per_core is None
            and k.system_reserved_cpu_millis == 0
            and k.system_reserved_memory_bytes == 0
            and k.kube_reserved_cpu_millis is None
            and k.kube_reserved_memory_bytes is None
            and k.eviction_hard_memory_bytes == DEFAULT_EVICTION_HARD)


def kubelet_overhead_vector(k) -> "list[int]":
    """Per-node overhead a provisioner's kubelet config adds ON TOP of the
    instance type's built-in overhead (which already carries the default
    kubeReserved curve + default eviction threshold — providers/
    instancetypes.py node_overhead). kubeReserved/systemReserved here are
    additional reservations; evictionHard adds only its delta over the
    default. (Reference analogue: instancetype.go:229-319 capacity math,
    re-derived for a catalog whose defaults are pre-baked.)"""
    extra_cpu = k.system_reserved_cpu_millis + (k.kube_reserved_cpu_millis or 0)
    extra_mem = (k.system_reserved_memory_bytes
                 + (k.kube_reserved_memory_bytes or 0)
                 + max(0, k.eviction_hard_memory_bytes - DEFAULT_EVICTION_HARD))
    vec = [0] * wk.NUM_RESOURCES
    vec[wk.RESOURCE_INDEX[wk.RESOURCE_CPU]] = extra_cpu
    vec[wk.RESOURCE_INDEX[wk.RESOURCE_MEMORY]] = -(-extra_mem // 2**20)  # ceil MiB
    return vec


def kubelet_pods_cap(k, itype: InstanceType, cores: Optional[int] = None) -> Optional[int]:
    """Max pods per node of this type under the kubelet config (maxPods /
    podsPerCore, whichever is tighter; instancetype.go:321+ `pods`).
    `cores` avoids re-deriving the type's core count in Pv*T loops
    (models/encode.py kubelet_arrays)."""
    cap: Optional[int] = None
    if k.max_pods is not None:
        cap = k.max_pods
    if k.pods_per_core is not None:
        if cores is None:
            cores = max(1, dict(itype.capacity).get(wk.RESOURCE_CPU, 1000) // 1000)
        per_core = k.pods_per_core * cores
        cap = per_core if cap is None else min(cap, per_core)
    return cap


def effective_alloc(opt: Option, prov: Provisioner) -> "tuple[int, ...]":
    """Option allocatable under the provisioner's kubelet pods cap."""
    cap = kubelet_pods_cap(prov.kubelet, opt.itype)
    if cap is None:
        return opt.alloc
    alloc = list(opt.alloc)
    pi = wk.RESOURCE_INDEX[wk.RESOURCE_PODS]
    alloc[pi] = min(alloc[pi], cap)
    return tuple(alloc)


def option_labels(opt: Option, prov: Provisioner) -> "dict[str, str]":
    labels = opt.itype.labels_dict()
    labels[wk.LABEL_ZONE] = opt.zone
    labels[wk.LABEL_CAPACITY_TYPE] = opt.capacity_type
    labels[wk.LABEL_PROVISIONER] = prov.name
    for k, v in prov.labels:
        labels.setdefault(k, v)
    return labels


def feasible_options(
    group: PodSpec,
    prov: Provisioner,
    options: Sequence[Option],
    daemon_overhead: Sequence[int],
    barred: "frozenset[int] | set[int]" = frozenset(),
) -> "set[int]":
    """Options admitting ONE pod of this spec on a fresh node of `prov`.

    Mirrors resolveInstanceTypes' compatible ∧ available ∧ fits filter
    (cloudprovider.go:302-321). `barred` option indices (the spot plane's
    diversity floor) are excluded BEFORE preference relaxation — the
    kernel folds its option mask into availability ahead of the prefix
    choice (models/encode.py combine_group), so the scalar walk must too
    or the two paths pick different preference prefixes."""
    if not tolerates_all(group.tolerations, prov.taints):
        return set()
    try:
        reqs = prov.scheduling_requirements().union(group.requirements)
    except IncompatibleError:
        return set()
    vec = group.resource_vector()
    kovh = kubelet_overhead_vector(prov.kubelet)

    def feasible(r: Requirements) -> "set[int]":
        out: "set[int]" = set()
        for opt in options:
            if opt.index in barred:
                continue
            if not r.matches_labels(option_labels(opt, prov)):
                continue
            alloc = effective_alloc(opt, prov)
            if all(d + k + v <= a
                   for d, k, v, a in zip(daemon_overhead, kovh, vec, alloc)):
                out.add(opt.index)
        return out

    base = feasible(reqs)
    # Iterative preference relaxation (PodSpec.preferences docstring): take
    # the LARGEST prefix of weight-ordered preference terms that still leaves
    # at least one feasible option; terms drop lowest-weight first.
    if base and group.preferences:
        for k in range(len(group.preferences), 0, -1):
            try:
                r = reqs
                for term in group.preferences[:k]:
                    r = r.union(term)
            except IncompatibleError:
                continue
            preferred = feasible(r)
            if preferred:
                return preferred
    return base


@dataclasses.dataclass
class NodeClaim:
    """A node under construction (karpenter-core "Machine"/node claim)."""

    provisioner: Provisioner
    options: "set[int]"
    used: "list[int]"
    pods: "list[PodSpec]" = dataclasses.field(default_factory=list)
    group_counts: "dict[object, int]" = dataclasses.field(default_factory=dict)
    decided: Optional[Option] = None

    def decide(self, options: Sequence[Option]) -> Option:
        if self.decided is None:
            self.decided = min(
                (options[i] for i in self.options), key=Option.sort_key
            )
        return self.decided


@dataclasses.dataclass
class ExistingNode:
    """An already-launched node considered during scheduling/consolidation
    (cluster state; state.NewCluster at main.go:54).

    `resident` carries the node's non-daemon pods so topology decisions can
    count what is ALREADY in each domain — zone-spread shares and per-node
    group caps (hostname spread / anti-affinity) must account for resident
    pods, matching the reference scheduler's domain-population counting
    (designs/bin-packing.md:28-43 grouping over existing nodes)."""

    name: str
    labels: "dict[str, str]"
    allocatable: "list[int]"
    used: "list[int]"
    taints: "tuple[Taint, ...]" = ()
    resident: "tuple[PodSpec, ...]" = ()
    # pods placed DURING the current scheduling run, keyed by ORIGIN key so
    # zone-split subgroups of one deployment share one per-node cap budget
    group_counts: "dict[object, int]" = dataclasses.field(default_factory=dict)
    # pods already resident BEFORE the run, keyed by (pre-split) group key.
    # Kept separate from group_counts so spreading counts residents only;
    # the kernel's static per-row ex_cap subtracts BOTH (resident base +
    # carried in-run counts, models/encode.py) — the same
    # resident_counts[okey] + group_counts[okey] rule this oracle checks.
    resident_counts: "dict[object, int]" = dataclasses.field(default_factory=dict)

    def __post_init__(self):
        if self.resident_counts:
            # pre-seeded (columnar snapshot: counts come off the node's
            # incremental aggregates, so `resident` can stay lazy)
            return
        # seed resident counts (same group_key space as the pending batch:
        # identical specs hash identically; residents are never zone-split)
        for p in self.resident:
            k = p.group_key()
            self.resident_counts[k] = self.resident_counts.get(k, 0) + 1

    def zone(self) -> str:
        return self.labels.get(wk.LABEL_ZONE, "")

    def effective_labels(self) -> "dict[str, str]":
        """labels with the hostname defaulted to the node name — pod-affinity
        pins target hostname, and kubelet always sets that label on real
        nodes even when a fake/test node omits it."""
        if wk.LABEL_HOSTNAME in self.labels:
            return self.labels
        d = dict(self.labels)
        d[wk.LABEL_HOSTNAME] = self.name
        return d

    def fits(self, group: PodSpec, vec: Sequence[int]) -> bool:
        if not tolerates_all(group.tolerations, self.taints):
            return False
        if not group.requirements.matches_labels(self.effective_labels()):
            return False
        return all(u + v <= a for u, v, a in zip(self.used, vec, self.allocatable))


@dataclasses.dataclass
class SchedulingResult:
    new_nodes: "list[NodeClaim]"
    existing_assignments: "dict[str, list[PodSpec]]"
    unschedulable: "list[PodSpec]"

    def node_decisions(self, options: Sequence[Option]) -> "list[tuple[str, str, str, int]]":
        """[(instance type, zone, capacityType, pod count)] sorted — the
        decision fingerprint used for kernel/oracle parity checks."""
        out = []
        for n in self.new_nodes:
            opt = n.decide(options)
            out.append((opt.itype.name, opt.zone, opt.capacity_type, len(n.pods)))
        return sorted(out)


def _group_cap_per_node(spec: PodSpec) -> Optional[int]:
    """Max pods of one group on one node, from hostname topology/anti-affinity.

    Hostname anti-affinity => 1. Hostname spread with maxSkew s => s (each new
    node is a fresh domain with zero pods; skew bound caps the run). Zone
    spread is handled by the zone pre-pass, not here.
    """
    cap: Optional[int] = None
    if spec.anti_affinity_hostname:
        cap = 1
    for c in spec.topology:
        if c.topology_key == wk.LABEL_HOSTNAME and c.when_unsatisfiable == "DoNotSchedule":
            cap = c.max_skew if cap is None else min(cap, c.max_skew)
    return cap


def resolve_pod_affinity(groups: "list[PodGroup]", zones: Sequence[str],
                         existing: "Sequence[ExistingNode]" = ()) -> "list[PodGroup]":
    """Pre-pass: required pod-(anti-)affinity terms -> node requirements.

    Runs BEFORE zone splitting so affinity-derived zone constraints narrow
    the spread domains. Semantics (approximating core inter-pod affinity,
    test/suites/integration/scheduling_test.go):

    - zone AFFINITY: the pod may only go to zones that hold a matching
      resident pod, or zones a matching co-pending group can land in (its
      explicit zone requirement, else any zone). No candidates at all =>
      unschedulable (pinned to the sentinel zone), matching k8s required
      semantics. A selector matching the group's own labels is satisfiable
      anywhere the group itself can go (the k8s first-pod bootstrap rule).
    - hostname AFFINITY: with matching residents, pin to those nodes
      (hostname In [...] — fresh options carry no hostname, so only those
      nodes fit). Matching CO-PENDING pods are handled by the two-round
      solve (split_deferred_pods): the dependent group is deferred, the
      target's round-1 claims join `existing` as pseudo nodes, and this
      same resident pin applies — hard co-location.
    - zone/hostname ANTI-affinity with a non-self selector: exclude the
      domains that hold matching residents (NotIn; fresh options lack the
      hostname key, so NotIn admits them). Anti-affinity BETWEEN co-pending
      groups also resolves through the two-round solve (the target's
      claims/zones become resident domains to exclude); self-selecting
      anti-affinity uses the anti_affinity_* booleans. Greedy first-wins:
      dependency chains deeper than one round stay best-effort (the
      sequential kube-scheduler has the same horizon).

    THE HORIZON BOUND (adversarially pinned by tests/test_affinity_horizon.py):
    one dependency level resolves per solve. The tail of a deeper chain
    PENDS — it is never placed in violation of its term — and retrying
    with each cycle's claims bound as existing nodes converges one level
    per reconcile cycle (depth-k chains converge in <= k-1 cycles).
    Anti-affinity never co-locates a violating pair at any depth.
    """
    has_terms = any(g.spec.pod_affinity or g.spec.pod_anti_affinity
                    for g in groups)
    if not has_terms:
        return groups

    def pending_zones(term) -> "tuple[set[str], bool]":
        """(zones matching co-pending groups can use, any_match)."""
        out: "set[str]" = set()
        any_match = False
        for og in groups:
            if not term.matches(og.spec.labels):
                continue
            any_match = True
            zreq = og.spec.requirements.get(wk.LABEL_ZONE)
            out |= {z for z in zones if zreq is None or zreq.has(z)}
        return out, any_match

    out: "list[PodGroup]" = []
    for g in groups:
        spec = g.spec
        if not spec.pod_affinity and not spec.pod_anti_affinity:
            out.append(g)
            continue
        reqs = spec.requirements.copy()
        feasible = True
        for term in spec.pod_affinity:
            if term.topology_key == wk.LABEL_ZONE:
                cand = {e.zone() for e in existing
                        if any(term.matches(p.labels) for p in e.resident)}
                pend, any_pend = pending_zones(term)
                cand |= pend
                cand &= set(zones)
                if not cand:
                    feasible = False
                    break
                if cand != set(zones):
                    try:
                        reqs.add(Requirement.create(
                            wk.LABEL_ZONE, OP_IN, sorted(cand)))
                    except IncompatibleError:
                        feasible = False
                        break
            elif term.topology_key == wk.LABEL_HOSTNAME:
                hosts = sorted(
                    e.labels.get(wk.LABEL_HOSTNAME, e.name) for e in existing
                    if any(term.matches(p.labels) for p in e.resident))
                if hosts:
                    try:
                        reqs.add(Requirement.create(
                            wk.LABEL_HOSTNAME, OP_IN, hosts))
                    except IncompatibleError:
                        feasible = False
                        break
                elif not term.matches(spec.labels) \
                        and not pending_zones(term)[1]:
                    feasible = False  # nothing to co-locate with anywhere
                    break
        for term in spec.pod_anti_affinity:
            if not feasible:
                break
            if term.topology_key == wk.LABEL_ZONE:
                forbidden = sorted(
                    {e.zone() for e in existing
                     if any(term.matches(p.labels) for p in e.resident)})
                if forbidden:
                    try:
                        reqs.add(Requirement.create(
                            wk.LABEL_ZONE, "NotIn", forbidden))
                    except IncompatibleError:
                        feasible = False
                        break
            elif term.topology_key == wk.LABEL_HOSTNAME:
                forbidden = sorted(
                    e.labels.get(wk.LABEL_HOSTNAME, e.name) for e in existing
                    if any(term.matches(p.labels) for p in e.resident))
                if forbidden:
                    try:
                        reqs.add(Requirement.create(
                            wk.LABEL_HOSTNAME, "NotIn", forbidden))
                    except IncompatibleError:
                        feasible = False
                        break
        if not feasible:
            reqs = Requirements.of((wk.LABEL_ZONE, OP_IN, ["__no-zone__"]))
        new_spec = dataclasses.replace(g.spec, requirements=reqs,
                                       spread_origin=g.spec.origin_key())
        out.append(PodGroup(spec=new_spec, count=g.count,
                            pod_names=g.pod_names))
    return out


def water_fill_shares(resident: "dict[str, int]", allowed: "list[str]",
                      count: int) -> "dict[str, int]":
    """Closed-form water filling: the exact distribution the sequential
    "each pod goes to the (lowest-population, lexicographically-first)
    domain" loop produces, in O(Z log Z) instead of O(pods x Z).

    Level L fills every domain below it; the remainder lands one pod each on
    the name-ordered prefix of the domains sitting at or below L (matching
    the sequential tie-break). Differential-tested against the scalar loop
    in tests/test_oracle_scheduler.py."""
    levels = sorted(resident[z] for z in allowed)
    n_z = len(allowed)
    # find the highest fully-reachable level L: cost(L) = sum(max(0, L-c_z))
    lo = levels[0]
    hi = levels[-1] + (count // n_z) + 1
    while lo < hi:  # binary search the largest L with cost(L) <= count
        mid = (lo + hi + 1) // 2
        cost = sum(mid - c for c in levels if c < mid)
        if cost <= count:
            lo = mid
        else:
            hi = mid - 1
    L = lo
    shares = {z: max(0, L - resident[z]) for z in allowed}
    leftover = count - sum(shares.values())
    if leftover:
        # one pod each to the name-ordered prefix of domains at level <= L
        at_level = sorted(z for z in allowed if resident[z] <= L)
        for z in at_level[:leftover]:
            shares[z] += 1
    return shares


def split_zone_spread(groups: "list[PodGroup]", zones: Sequence[str],
                      existing: "Sequence[ExistingNode]" = ()) -> "list[PodGroup]":
    """Pre-pass: groups with a zone topology-spread constraint are split into
    per-zone subgroups, shares assigned by WATER-FILLING over the pods the
    group ALREADY has resident in each zone (each new pod goes to the domain
    with the lowest current population — always satisfies maxSkew >= 1,
    matching the reference scheduler's domain-population counting,
    designs/bin-packing.md:28-43).

    `DoNotSchedule` subgroups get a hard zone requirement. `ScheduleAnyway`
    subgroups get a SOFT zone preference term (appended lowest-priority): the
    scheduler's iterative relaxation drops it when the zone can't host the
    pod, so spreading is best-effort exactly as k8s specifies.

    Reference analogue: the scheduler's topology domain narrowing; E2E
    spread-zone.yaml expects even distribution across AZs.
    """
    out: "list[PodGroup]" = []
    for g in groups:
        hard = any(c.topology_key == wk.LABEL_ZONE
                   and c.when_unsatisfiable == "DoNotSchedule"
                   for c in g.spec.topology)
        soft = any(c.topology_key == wk.LABEL_ZONE
                   and c.when_unsatisfiable == "ScheduleAnyway"
                   for c in g.spec.topology)
        if not hard and not soft and not g.spec.anti_affinity_zone:
            out.append(g)
            continue
        zreq = g.spec.requirements.get(wk.LABEL_ZONE)
        allowed = [z for z in sorted(zones) if zreq is None or zreq.has(z)]
        if not allowed:
            out.append(g)
            continue
        # domain population: pods of this group already resident per zone
        # (ORIGIN key: an earlier pre-pass, e.g. pod-affinity resolution,
        # may have rewritten the spec, while residents keep the original)
        gkey = g.spec.origin_key()
        resident = {z: 0 for z in allowed}
        for e in existing:
            ez = e.zone()
            if ez in resident:
                resident[ez] += e.resident_counts.get(gkey, 0)
        if g.spec.anti_affinity_zone:
            # one pod per zone, counting residents; surplus pods are
            # unschedulable (pinned to the sentinel zone no offering carries)
            open_zones = [z for z in allowed if resident[z] == 0]
            shares = [1 if i < g.count else 0 for i in range(len(open_zones))]
            allowed = open_zones
            surplus = g.count - sum(shares)
        else:
            share_of = water_fill_shares(resident, allowed, g.count)
            shares = [share_of[z] for z in allowed]
            surplus = 0
        pos = 0
        for z, share in zip(allowed, shares):
            if share == 0:
                continue
            if hard or g.spec.anti_affinity_zone:
                try:
                    reqs = g.spec.requirements.copy()
                    reqs.add(Requirement.create(wk.LABEL_ZONE, OP_IN, [z]))
                except IncompatibleError:
                    continue
                spec = dataclasses.replace(g.spec, requirements=reqs,
                                           spread_origin=gkey)
            else:
                # ScheduleAnyway: soft zone pin, dropped first by relaxation
                spec = dataclasses.replace(
                    g.spec, spread_origin=gkey,
                    preferences=g.spec.preferences + (
                        Requirements.of((wk.LABEL_ZONE, OP_IN, [z])),))
            out.append(PodGroup(spec=spec, count=share, pod_names=g.pod_names[pos:pos + share]))
            pos += share
        if surplus > 0:
            spec = dataclasses.replace(g.spec, requirements=Requirements.of(
                (wk.LABEL_ZONE, OP_IN, ["__no-zone__"])), spread_origin=gkey)
            out.append(PodGroup(spec=spec, count=surplus, pod_names=g.pod_names[pos:pos + surplus]))
    return out


def split_deferred_pods(pods: "list[PodSpec]") -> "tuple[list[PodSpec], list[PodSpec]]":
    """(primary, deferred) for the two-round co-pending affinity solve.

    A group whose required pod-(anti-)affinity terms match another CO-PENDING
    group kept in the primary round is deferred: round 1 places the targets,
    their claims are then presented to round 2 as existing nodes (with the
    target pods as residents), and the resident-based affinity machinery —
    hostname In pins, domain NotIn exclusions, per-node resident caps —
    resolves the co-pending terms exactly as it does for real residents.

    Greedy first-wins ordering (matching the sequential kube-scheduler):
    mutual/cyclic dependencies keep the first group in round 1 and defer the
    rest; chains deeper than one round stay best-effort.
    """
    # fast path: no affinity terms anywhere -> no second round. An attribute
    # scan is ~10x cheaper than the full dedup grouping at 10k pods, and the
    # headline workloads carry no terms (profiled round 3). Plain loop, not
    # any(genexpr): the generator frame resume per pod is ~0.4ms at 10k.
    for p in pods:
        if p.pod_affinity or p.pod_anti_affinity:
            break
    else:
        return list(pods), []
    groups = group_pods([p for p in pods if not p.is_daemon()])
    # a group defers when any of its terms matches ANOTHER co-pending group
    # regardless of input order (forward references included); cycle
    # breaking is first-wins: a candidate whose every target already
    # deferred stays primary so the deferred targets can see ITS placements
    def targets_of(spec: PodSpec) -> "list[PodSpec]":
        out = []
        for term in tuple(spec.pod_affinity) + tuple(spec.pod_anti_affinity):
            out.extend(og.spec for og in groups
                       if og.spec is not spec and term.matches(og.spec.labels))
        return out

    deferred_keys: "set" = set()
    for g in groups:
        tgts = targets_of(g.spec)
        if tgts and any(t.group_key() not in deferred_keys for t in tgts):
            deferred_keys.add(g.spec.group_key())
    if not deferred_keys:
        return list(pods), []
    primary: "list[PodSpec]" = []
    deferred: "list[PodSpec]" = []
    for p in pods:
        if not p.is_daemon() and p.group_key() in deferred_keys:
            deferred.append(p)
        else:
            primary.append(p)
    return primary, deferred


def prepare_groups(pods: "list[PodSpec]", zones: Sequence[str],
                   existing: "Sequence[ExistingNode]" = ()) -> "list[PodGroup]":
    """Dedupe -> zone-spread split (domain-population aware) -> FFD sort
    (bin-packing.md step 1).

    Shared verbatim between this oracle and the kernel encoder
    (models/encode.py) so group ordering — which FFD results depend on —
    is identical on both paths."""
    # attribute compare, not is_daemon(): 10k bound-method calls are ~1ms
    # of the per-cycle host encode budget
    groups = group_pods([p for p in pods if p.owner_kind != "DaemonSet"])
    groups = resolve_pod_affinity(groups, zones, existing)
    groups = split_zone_spread(groups, zones, existing)
    groups.sort(key=lambda g: (
        -g.vector[wk.RESOURCE_INDEX[wk.RESOURCE_CPU]],
        -g.vector[wk.RESOURCE_INDEX[wk.RESOURCE_MEMORY]],
        g.spec.name,
    ))
    return groups


class Scheduler:
    """FFD bin-packing over pod groups (the provisioning hot loop,
    designs/bin-packing.md:17-43)."""

    def __init__(
        self,
        catalog: Catalog,
        provisioners: Sequence[Provisioner],
        daemon_overhead: Optional[Sequence[int]] = None,
        barred: "Optional[set[tuple[str, str, str]]]" = None,
    ):
        self.catalog = catalog
        self.options = build_options(catalog)
        # the zone-spread universe is computed BEFORE the barred filter —
        # parity with the kernel path, where active_zones() folds only
        # availability, never the spot plane's diversity option mask
        self.zones = sorted({o.zone for o in self.options})
        # weight desc, then name asc (core: higher weight preferred)
        self.provisioners = sorted(provisioners, key=lambda p: (-p.weight, p.name))
        self.daemon_overhead = list(daemon_overhead or [0] * wk.NUM_RESOURCES)
        self._eff_cache: "dict[tuple[str, int], tuple[int, ...]]" = {}
        # barred (instance type, zone, capacityType) pools — the scalar
        # analogue of encode_problem's option_mask: removed from NEW-node
        # admission only (existing-node fits are untouched on both paths)
        self._barred: "set[int]" = set() if not barred else {
            o.index for o in self.options
            if (o.itype.name, o.zone, o.capacity_type) in barred}

    def _eff_alloc(self, prov: Provisioner, opt_index: int) -> "tuple[int, ...]":
        key = (prov.name, opt_index)
        a = self._eff_cache.get(key)
        if a is None:
            a = self._eff_cache[key] = effective_alloc(self.options[opt_index], prov)
        return a

    def schedule(
        self,
        pods: "list[PodSpec]",
        existing: "Iterable[ExistingNode]" = (),
    ) -> SchedulingResult:
        """Two-round driver: groups with co-pending affinity targets are
        deferred; round 1's claims join `existing` for round 2 so the
        resident-based affinity logic resolves them (split_deferred_pods)."""
        existing = list(existing)
        primary, deferred = split_deferred_pods(pods)
        if not deferred:
            return self._schedule_once(pods, existing)
        res = self._schedule_once(primary, existing)
        pseudo = self._claims_as_existing(res.new_nodes)
        res2 = self._schedule_once(deferred, existing + pseudo)
        # merge: dependents placed on round-1 claims fold back into them
        by_name = {p.name: (p, claim) for p, claim in
                   zip(pseudo, res.new_nodes)}
        for name, placed in list(res2.existing_assignments.items()):
            hit = by_name.get(name)
            if hit is None:
                res.existing_assignments.setdefault(name, []).extend(placed)
                continue
            hit[1].pods.extend(placed)
        res.new_nodes.extend(res2.new_nodes)
        res.unschedulable.extend(res2.unschedulable)
        return res

    def _claims_as_existing(self, claims: "list[NodeClaim]") -> "list[ExistingNode]":
        """Round-1 claims as existing nodes: labels of the decided option,
        remaining capacity under that option, the claim's pods as residents."""
        out = []
        for i, n in enumerate(claims):
            opt = n.decide(self.options)
            out.append(ExistingNode(
                name=f"__round1-claim-{i}",
                labels=option_labels(opt, n.provisioner),
                allocatable=list(effective_alloc(opt, n.provisioner)),
                used=list(n.used),
                taints=n.provisioner.taints,
                resident=tuple(n.pods),
            ))
        return out

    def _schedule_once(
        self,
        pods: "list[PodSpec]",
        existing: "list[ExistingNode]",
    ) -> SchedulingResult:
        groups = prepare_groups(pods, self.zones, existing)

        feas_cache: "dict[tuple[int, str], set[int]]" = {}
        nodes: "list[NodeClaim]" = []
        assignments: "dict[str, list[PodSpec]]" = {e.name: [] for e in existing}
        unschedulable: "list[PodSpec]" = []

        for gi, g in enumerate(groups):
            vec = g.vector
            cap = _group_cap_per_node(g.spec)
            # All in-run per-node counting is keyed by the ORIGIN key: resident
            # pods carry their pre-split spec, and ScheduleAnyway zone-split
            # subgroups share hard requirements (they differ only in soft
            # preferences), so two soft subgroups of one capped deployment
            # must share one per-node budget. Hard zone subgroups can never
            # share a node anyway (disjoint zone pins), so origin-keyed
            # counting is strictly safe on both existing nodes and claims.
            okey = g.spec.origin_key()
            for _ in range(g.count):
                placed = False
                # 1) existing cluster nodes first (in-flight awareness,
                #    bin-packing.md grouping + core scheduler behavior)
                for e in existing:
                    # cap = resident base + pods this run placed of any
                    # subgroup sharing the origin — the same static-base +
                    # shared-budget rule the kernel's ex_cap waterfall applies
                    if cap is not None and (
                            e.resident_counts.get(okey, 0)
                            + e.group_counts.get(okey, 0)) >= cap:
                        continue
                    if e.fits(g.spec, vec):
                        e.used = [u + v for u, v in zip(e.used, vec)]
                        e.group_counts[okey] = e.group_counts.get(okey, 0) + 1
                        assignments[e.name].append(g.spec)
                        placed = True
                        break
                if placed:
                    continue
                # 2) first open node claim whose option set still admits the pod
                for n in nodes:
                    if cap is not None and n.group_counts.get(okey, 0) >= cap:
                        continue
                    pk = (gi, n.provisioner.name)
                    if pk not in feas_cache:
                        feas_cache[pk] = feasible_options(
                            g.spec, n.provisioner, self.options,
                            self.daemon_overhead, barred=self._barred
                        )
                    shared = n.options & feas_cache[pk]
                    if not shared:
                        continue
                    new_used = [u + v for u, v in zip(n.used, vec)]
                    fitting = {
                        i for i in shared
                        if all(u <= a for u, a in zip(
                            new_used, self._eff_alloc(n.provisioner, i)))
                    }
                    if not fitting:
                        continue
                    n.options = fitting
                    n.used = new_used
                    n.pods.append(g.spec)
                    n.group_counts[okey] = n.group_counts.get(okey, 0) + 1
                    placed = True
                    break
                if placed:
                    continue
                # 3) open a new node: first provisioner (weight order) that admits
                for prov in self.provisioners:
                    pk2 = (gi, prov.name)
                    if pk2 not in feas_cache:
                        feas_cache[pk2] = feasible_options(
                            g.spec, prov, self.options,
                            self.daemon_overhead, barred=self._barred
                        )
                    if feas_cache[pk2]:
                        kovh = kubelet_overhead_vector(prov.kubelet)
                        nodes.append(NodeClaim(
                            provisioner=prov,
                            options=set(feas_cache[pk2]),
                            used=[d + k + v for d, k, v in zip(
                                self.daemon_overhead, kovh, vec)],
                            pods=[g.spec],
                            group_counts={okey: 1},
                        ))
                        placed = True
                        break
                if not placed:
                    unschedulable.append(g.spec)

        for n in nodes:
            n.decide(self.options)
        return SchedulingResult(nodes, assignments, unschedulable)
