"""Roofline cost model keyed on the BucketPlan rung.

Every solve dispatches one pack kernel at a padded rung shape
``(Gb groups, Nb slots, Neb existing)`` (solver/buckets.py LADDERS), so the
bytes that must cross the host-device boundary and the FLOPs the kernel
must execute are *functions of the rung*, not of the live pod set. That
gives a theoretical floor per solve:

    floor = max(bytes_moved / peak_bandwidth,
                flops / (peak_flops * device_count))

The floor is deliberately optimistic (it prices neither dispatch latency
nor XLA link time) — its job is to be the denominator of
``karpenter_profile_roofline_ratio`` (measured device-exec / floor). A
ratio near 1 means the device phase is at the hardware limit and the
remaining headline milliseconds live on the host side of the gap ledger;
a large ratio means the device phase itself is leaving performance on the
table. Monotone in every rung dimension by construction (sums and maxima
of monotone terms), which tests/test_profiling.py locks in.

Byte model (matches build_pack_inputs' per-solve delta — the catalog
arrays are device-resident and NOT counted, SURVEY.md §7.3 "ship only the
pod delta"):

    h2d  = Gb·(R·4 + 3·4)            group vec / count / cap / newprov
         + Gb·Pv                      feasibility mask (bool)
         + Neb·(2·R·4)                existing alloc + used
         + Gb·Neb                     existing feasibility (bool)
         + R·4                        daemon overhead
    d2h  = Nb·4 + Gb·4 + Neb·4 + 64   flat result + headers
    flops = Gb·Nb·T·S·OPS_PER_CELL    per-slot-step candidate scan

Peaks are per-backend defaults overridable with
``KARPENTER_TPU_ROOFLINE_GBPS`` / ``KARPENTER_TPU_ROOFLINE_GFLOPS``
(warn-and-fallback on garbage, the crossover_cells_default idiom).
"""
from __future__ import annotations

import logging
import os
import threading
from typing import NamedTuple

from ..metrics import REGISTRY

log = logging.getLogger(__name__)

DTYPE_BYTES = 4
#: modelled kernel work per candidate cell per tiebreak step: feasibility
#: compare, capacity subtract, score blend, argmin update (vectorised).
OPS_PER_CELL = 8

BW_ENV = "KARPENTER_TPU_ROOFLINE_GBPS"
FLOPS_ENV = "KARPENTER_TPU_ROOFLINE_GFLOPS"

#: per-backend (bandwidth GB/s, compute GFLOP/s) defaults. The TPU row is
#: a v4-class HBM/VPU envelope; the CPU row is a single-socket host — both
#: are deliberately round: the ratio gauge is for trend-spotting, not
#: datasheet accounting.
PEAKS = {
    "tpu": (1200.0, 45_000.0),
    "gpu": (900.0, 30_000.0),
    "cpu": (20.0, 50.0),
}

ROOFLINE_BYTES = REGISTRY.gauge(
    "karpenter_profile_roofline_bytes",
    "Modelled bytes crossing the host-device boundary per solve at this rung",
    ("bucket",))
ROOFLINE_FLOPS = REGISTRY.gauge(
    "karpenter_profile_roofline_flops",
    "Modelled kernel FLOPs per solve at this rung",
    ("bucket",))
ROOFLINE_FLOOR_MS = REGISTRY.gauge(
    "karpenter_profile_roofline_floor_ms",
    "Theoretical per-solve floor ms = max(bytes/bw, flops/peak) at this rung",
    ("bucket",))
ROOFLINE_RATIO = REGISTRY.gauge(
    "karpenter_profile_roofline_ratio",
    "Measured device-exec ms / roofline floor ms (1.0 = at the roofline)",
    ("bucket",))

# -- the measured (not modelled) floor (ISSUE 18) ------------------------------
# At compile-cache warmup the solver captures `compiled.cost_analysis()` /
# `memory_analysis()` per BucketPlan rung — XLA's own bytes/FLOPs for the
# exact compiled program — so the floor the kernel arc chases is the
# compiler's number. The modelled gauges above survive for trend
# continuity; drift between the two >DRIFT_THRESHOLD× is a warning event
# plus a statusz flag (the hand model silently diverging from the real
# program is exactly the failure mode this layer exists to catch).

#: modelled-vs-measured FLOPs ratio above which the model is flagged
DRIFT_THRESHOLD = 2.0

ROOFLINE_MEASURED_BYTES = REGISTRY.gauge(
    "karpenter_profile_roofline_measured_bytes",
    "XLA cost_analysis bytes accessed per solve at this rung",
    ("bucket",))
ROOFLINE_MEASURED_FLOPS = REGISTRY.gauge(
    "karpenter_profile_roofline_measured_flops",
    "XLA cost_analysis FLOPs per solve at this rung",
    ("bucket",))
ROOFLINE_MEASURED_FLOOR_MS = REGISTRY.gauge(
    "karpenter_profile_roofline_measured_floor_ms",
    "Per-solve floor ms = max(bytes/bw, flops/peak) from MEASURED numbers",
    ("bucket",))

_measured_lock = threading.Lock()
_measured: "dict[str, dict]" = {}


class Roofline(NamedTuple):
    bucket: str
    bytes_moved: int
    flops: int
    floor_ms: float
    bw_gbps: float
    peak_gflops: float
    backend: str
    device_count: int


def _env_float(env: str, fallback: float) -> float:
    raw = os.environ.get(env)
    if raw is None:
        return fallback
    try:
        v = float(raw)
        if v <= 0:
            raise ValueError(raw)
        return v
    except ValueError:
        log.warning("%s=%r invalid (want a positive number); using %s",
                    env, raw, fallback)
        return fallback


def peaks_for(backend: str) -> "tuple[float, float]":
    bw, fl = PEAKS.get(backend, PEAKS["cpu"])
    return _env_float(BW_ENV, bw), _env_float(FLOPS_ENV, fl)


def estimate(groups: int, slots: int, existing: int, *,
             pv: int = 1, t: int = 16, s: int = 4,
             resources: int = 8, device_count: int = 1,
             backend: str = "cpu", bucket: str = "") -> Roofline:
    """Roofline for one solve at the padded rung (duck-typed on the
    BucketPlan dims so hack/ lints can call it without importing jax)."""
    gb, nb, neb = max(1, int(groups)), max(1, int(slots)), max(0, int(existing))
    pv = max(1, int(pv))
    h2d = (gb * (resources * DTYPE_BYTES + 3 * DTYPE_BYTES)
           + gb * pv
           + neb * (2 * resources * DTYPE_BYTES)
           + gb * neb
           + resources * DTYPE_BYTES)
    d2h = nb * DTYPE_BYTES + gb * DTYPE_BYTES + neb * DTYPE_BYTES + 64
    flops = gb * nb * max(1, int(t)) * max(1, int(s)) * OPS_PER_CELL
    bw_gbps, peak_gflops = peaks_for(backend)
    dc = max(1, int(device_count))
    floor_s = max((h2d + d2h) / (bw_gbps * 1e9),
                  flops / (peak_gflops * 1e9 * dc))
    return Roofline(
        bucket=bucket or f"g{gb}n{nb}e{neb}",
        bytes_moved=h2d + d2h,
        flops=flops,
        floor_ms=floor_s * 1e3,
        bw_gbps=bw_gbps,
        peak_gflops=peak_gflops,
        backend=backend,
        device_count=dc,
    )


def observe(rf: Roofline, device_exec_ms: float) -> float:
    """Publish the rung's roofline gauges; returns the measured/floor ratio
    (callers record it into the gap-ledger row)."""
    ROOFLINE_BYTES.set(float(rf.bytes_moved), bucket=rf.bucket)
    ROOFLINE_FLOPS.set(float(rf.flops), bucket=rf.bucket)
    ROOFLINE_FLOOR_MS.set(rf.floor_ms, bucket=rf.bucket)
    ratio = device_exec_ms / rf.floor_ms if rf.floor_ms > 0 else 0.0
    ROOFLINE_RATIO.set(ratio, bucket=rf.bucket)
    return ratio


def record_measured(bucket: str, *, flops: float, bytes_accessed: float,
                    backend: str = "cpu", device_count: int = 1,
                    modelled: "Roofline | None" = None,
                    memory_bytes: "float | None" = None) -> dict:
    """File one rung's XLA-measured cost numbers: publish the measured
    gauges, compute the measured floor against the same per-backend peaks
    the model uses, and run the drift check — modelled-vs-measured FLOPs
    ratio beyond DRIFT_THRESHOLD in either direction logs a warning event
    and flags the rung in the statusz snapshot (the drill and tests read
    the flag; flagged rungs are reported, never hidden).

    FLOPs compare like-for-like (same quantity, two estimators); the byte
    numbers measure DIFFERENT quantities (the model prices host<->device
    boundary traffic, cost_analysis prices total memory traffic inside
    the program), so the bytes delta is reported informationally and
    never flags."""
    fl = max(0.0, float(flops))
    by = max(0.0, float(bytes_accessed))
    bw_gbps, peak_gflops = peaks_for(backend)
    dc = max(1, int(device_count))
    floor_ms = max(by / (bw_gbps * 1e9),
                   fl / (peak_gflops * 1e9 * dc)) * 1e3
    entry = {
        "bucket": bucket,
        "backend": backend,
        "flops": fl,
        "bytes_accessed": by,
        "floor_ms": round(floor_ms, 6),
        "flagged": False,
    }
    if memory_bytes is not None:
        entry["memory_bytes"] = float(memory_bytes)
    if modelled is not None:
        entry["modelled_flops"] = float(modelled.flops)
        entry["modelled_bytes"] = float(modelled.bytes_moved)
        entry["modelled_floor_ms"] = round(modelled.floor_ms, 6)
        if fl > 0 and modelled.flops > 0:
            drift = max(fl / modelled.flops, modelled.flops / fl)
            entry["flops_drift"] = round(drift, 3)
            if drift > DRIFT_THRESHOLD:
                entry["flagged"] = True
                log.warning(
                    "roofline drift at rung %s: modelled %.3g FLOPs vs "
                    "measured %.3g (%.1fx > %.1fx) — the cost model has "
                    "diverged from the compiled program",
                    bucket, modelled.flops, fl, drift, DRIFT_THRESHOLD)
    ROOFLINE_MEASURED_BYTES.set(by, bucket=bucket)
    ROOFLINE_MEASURED_FLOPS.set(fl, bucket=bucket)
    ROOFLINE_MEASURED_FLOOR_MS.set(floor_ms, bucket=bucket)
    with _measured_lock:
        _measured[bucket] = entry
    return entry


def measured_snapshot() -> dict:
    """Per-rung measured entries + an any-rung-flagged rollup (the statusz
    `critical` section embeds this; the drill ledgers the deltas)."""
    with _measured_lock:
        rungs = {k: dict(v) for k, v in _measured.items()}
    return {
        "drift_threshold": DRIFT_THRESHOLD,
        "rungs": rungs,
        "drift_flagged": sorted(k for k, v in rungs.items()
                                if v.get("flagged")),
    }


def clear_measured() -> None:
    """Test hook: drop recorded measured entries."""
    with _measured_lock:
        _measured.clear()
