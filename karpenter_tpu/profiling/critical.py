"""Critical-path ledger: overlap-aware wait/work attribution (ISSUE 18).

The gap ledger is a FLAT decomposition — phases sum to the wall — which is
structurally blind to concurrency: once host encode overlaps device
execute (the ROADMAP 2a pipelining arc), the sum-to-wall invariant breaks
and the flat instrument can no longer say which phase gates the wall.
This module makes the same ``note()`` measurements speak intervals, lanes
and waits:

- every duration note becomes an Interval (monotonic start/end relative
  to the scope open, plus a LANE id: the encode thread, the fleet tick
  loop, the solver wave, the device stream, wire serialize), kept in a
  bounded per-solve interval list and a bounded cross-solve ring;
- the longest dependency chain over those intervals is the CRITICAL PATH
  (weighted longest chain of non-overlapping intervals — an interval can
  only depend on work that finished before it started);
- every phase splits into ``on_critical_path_ms`` / ``off_critical_path_ms``;
- gaps between consecutive intervals on a lane are classified into an
  explicit WAIT vocabulary (queue_wait / device_wait / encode_wait /
  lock_wait), and cross-thread waits the lane geometry cannot see (the
  fleet frontend's admission->dispatch queue time) are filed explicitly
  via ``GAP_LEDGER.note_wait``;
- ``karpenter_profile_overlap_ratio`` = 1 − critical_path / sum-of-work.
  On today's strictly serial path the chain contains EVERY interval in
  end order, both sums fold identically, and the ratio is exactly 0.0 —
  the baseline number the pipelining PR must move.

The flat ledger survives as a PROJECTION: ``project_flat(intervals)``
folds interval durations per phase in append order, bit-identical to the
``rec.phases`` accumulation the gap ledger never stopped doing — every
existing phases-sum-to-wall consumer is untouched.

Strict-noop contract (the profiling/state.py pattern): with
``KARPENTER_TPU_CRITICAL=0`` no interval is recorded, no wait is filed,
no counter moves and the ring stays empty — the chaos
``critical-strict-noop`` invariant diffs :func:`activity` to prove it.
"""
from __future__ import annotations

import contextlib
import logging
import os
import threading
import time
from bisect import bisect_right
from collections import deque
from typing import NamedTuple

from ..metrics import REGISTRY

log = logging.getLogger(__name__)

FLAG_ENV = "KARPENTER_TPU_CRITICAL"
_FALSY = ("0", "false", "off", "no")

_lock = threading.Lock()
_enabled = os.environ.get(FLAG_ENV, "1").strip().lower() not in _FALSY


def enabled() -> bool:
    return _enabled


def set_enabled(on: bool) -> bool:
    """Flip the plane; returns the previous state (restore token)."""
    global _enabled
    with _lock:
        prev = _enabled
        _enabled = bool(on)
        return prev


@contextlib.contextmanager
def disabled():
    """Scoped hard-off: overhead baselines and the chaos strict-noop drill."""
    prev = set_enabled(False)
    try:
        yield
    finally:
        set_enabled(prev)


#: interval lanes — WHERE work runs. hack/check_phase_accounting.py keeps
#: every literal ``lane=`` at a note() call site inside this tuple and
#: flags dead lanes, the PHASES-table contract applied to concurrency.
#:
#:   encode   host problem preparation (extract/warm_start/encode/decode)
#:   tick     the fleet frontend tick loop (admission -> wave dispatch)
#:   solver   the solver wave driver (host dispatch / XLA link)
#:   device   the device stream (the one blocking device->host fetch)
#:   wire     wire serialize at the service boundary
LANES = ("encode", "tick", "solver", "device", "wire")

#: wait vocabulary — WHY a lane sat idle between two work intervals.
#: Classified from lane geometry (precedence below) or filed explicitly
#: via GAP_LEDGER.note_wait (cross-thread waits a single-threaded lane
#: trace cannot see, e.g. the fleet queue).
WAITS = ("queue_wait", "device_wait", "encode_wait", "lock_wait")

#: default lane per gap phase — callers override with note(lane=...).
PHASE_LANES = {
    "extract": "encode",
    "warm_start": "encode",
    "encode": "encode",
    "decode": "encode",
    "serialize": "wire",
    "link": "solver",
    "device_exec": "device",
}

RING_ENV = "KARPENTER_TPU_CRITICAL_RING"
DEFAULT_RING = 256
#: per-solve interval bound: a runaway wave cannot grow one record
#: without limit (solve_many at max wave files ~4 notes per problem)
MAX_INTERVALS_PER_SOLVE = 4096
#: gaps shorter than this are timer jitter, not a wait (10 microseconds)
MIN_WAIT_S = 1e-5

OVERLAP_RATIO = REGISTRY.gauge(
    "karpenter_profile_overlap_ratio",
    "1 - critical_path/sum_of_work for the most recent solve "
    "(0 = strictly serial; the pipelining arc must raise this)",
    ("source",))
CRITICAL_PATH_MS = REGISTRY.gauge(
    "karpenter_profile_critical_path_ms",
    "Longest dependency chain of the most recent solve's intervals",
    ("source",))
WAIT_MS = REGISTRY.counter(
    "karpenter_profile_wait_ms_total",
    "Cumulative lane idle milliseconds by wait kind",
    ("wait",))


class Interval(NamedTuple):
    """One duration note as an interval on a lane. ``dur`` is the MEASURED
    duration (clamped >= 0 exactly like the flat accumulation clamps), and
    ``start = max(0, end - dur)`` so clock skew can never produce a
    negative interval; start/end are seconds relative to the scope open."""
    lane: str
    phase: str
    start: float
    end: float
    dur: float


def make_interval(lane: str, phase: str, rel_end: float,
                  seconds: float) -> Interval:
    dur = max(0.0, seconds)
    end = max(0.0, rel_end)
    return Interval(lane, phase, max(0.0, end - dur), end, dur)


def _ring_cap() -> int:
    raw = os.environ.get(RING_ENV)
    if raw is None:
        return DEFAULT_RING
    try:
        v = int(raw)
        if v <= 0:
            raise ValueError(raw)
        return min(v, 65536)
    except ValueError:
        log.warning("%s=%r invalid (want a positive integer); using %d",
                    RING_ENV, raw, DEFAULT_RING)
        return DEFAULT_RING


# -- pure analysis (no state; tests drive these on synthetic DAGs) -------------


def project_flat(intervals: "list[Interval]") -> "dict[str, float]":
    """The flat gap-ledger projection: per-phase duration sums folded in
    APPEND order — the exact accumulation order ``GapLedger.note`` uses
    for ``rec.phases``, so the result is bit-identical to the flat row
    every existing consumer reads (tests assert equality, not closeness)."""
    out: "dict[str, float]" = {}
    for iv in intervals:
        out[iv.phase] = out.get(iv.phase, 0.0) + iv.dur
    return out


def critical_path(intervals: "list[Interval]") -> "tuple[float, list[int]]":
    """Longest weighted chain of non-overlapping intervals: interval j can
    precede i iff ``end_j <= start_i`` (work can only depend on work that
    had finished when it started). Returns (chain seconds, member indices
    into `intervals`). DP over end-sorted order with a prefix-max +
    bisect, O(n log n).

    Exact-0 serial guarantee: on a strictly serial trace the chain visits
    every interval in end order, accumulating ``dur_i + best`` — by IEEE
    commutativity that matches the left-fold ``sum()`` over the same order
    bit-for-bit, so ``analyze`` reports overlap_ratio exactly 0.0."""
    n = len(intervals)
    if n == 0:
        return 0.0, []
    order = sorted(range(n), key=lambda i: (intervals[i].end,
                                            intervals[i].start, i))
    ends = [intervals[i].end for i in order]
    best = [0.0] * n       # best chain sum ending at order[k]
    pred = [-1] * n        # predecessor in order-space
    # prefix_best[k] = (max over best[0..k], argmax) — monotone, so the
    # bisect below lands on the best chain finishing by start_i
    prefix_best = [0.0] * n
    prefix_arg = [0] * n
    for k, idx in enumerate(order):
        iv = intervals[idx]
        j = bisect_right(ends, iv.start, 0, k) - 1
        if j >= 0:
            best[k] = iv.dur + prefix_best[j]
            pred[k] = prefix_arg[j]
        else:
            best[k] = iv.dur
        if k == 0 or best[k] >= prefix_best[k - 1]:
            prefix_best[k] = best[k]
            prefix_arg[k] = k
        else:
            prefix_best[k] = prefix_best[k - 1]
            prefix_arg[k] = prefix_arg[k - 1]
    k = prefix_arg[n - 1]
    members: "list[int]" = []
    while k >= 0:
        members.append(order[k])
        k = pred[k]
    members.reverse()
    return best[prefix_arg[n - 1]], members


def classify_waits(intervals: "list[Interval]") -> "dict[str, float]":
    """Gap-between-intervals wait attribution: for each lane, the idle
    span between consecutive work intervals is classified by what the
    OTHER lanes were doing during it (precedence order: a busy device
    lane wins, then a busy encode lane; a gap on the tick lane with no
    busy producer is queue time; anything else is a lock/handoff wait)."""
    out = {w: 0.0 for w in WAITS}
    by_lane: "dict[str, list[Interval]]" = {}
    for iv in intervals:
        by_lane.setdefault(iv.lane, []).append(iv)

    def busy(lane: str, a: float, b: float) -> bool:
        return any(iv.end > a + MIN_WAIT_S and iv.start < b - MIN_WAIT_S
                   for iv in by_lane.get(lane, ()))

    for lane, ivs in by_lane.items():
        ivs = sorted(ivs, key=lambda iv: (iv.start, iv.end))
        frontier = ivs[0].end
        for iv in ivs[1:]:
            gap = iv.start - frontier
            if gap > MIN_WAIT_S:
                if lane != "device" and busy("device", frontier, iv.start):
                    out["device_wait"] += gap
                elif lane != "encode" and busy("encode", frontier, iv.start):
                    out["encode_wait"] += gap
                elif lane == "tick":
                    out["queue_wait"] += gap
                else:
                    out["lock_wait"] += gap
            frontier = max(frontier, iv.end)
    return out


def analyze(intervals: "list[Interval]",
            explicit_waits: "list[tuple[str, str, float]] | None" = None,
            wall_ms: "float | None" = None) -> dict:
    """The per-solve critical view: chain length, overlap ratio, per-phase
    on/off-critical split, wait breakdown (classified gaps + explicit
    notes). Pure — the ledger calls it at observe time, tests call it on
    hand-built DAGs. Ratio is structurally in [0, 1): the chain contains
    at least the longest single interval, so critical >= max(dur) > 0
    whenever any work was measured."""
    # sum-of-work folded over END-sorted order — the same order the DP
    # accumulates the serial chain in, which is what makes serial traces
    # report exactly 0.0 (see critical_path docstring)
    order = sorted(range(len(intervals)),
                   key=lambda i: (intervals[i].end, intervals[i].start, i))
    total_work = 0.0
    for i in order:
        total_work += intervals[i].dur
    crit, members = critical_path(intervals)
    member_set = set(members)
    ratio = 0.0
    if total_work > 0 and crit < total_work:
        ratio = 1.0 - crit / total_work
    ratio = min(max(ratio, 0.0), 1.0)
    on_ms: "dict[str, float]" = {}
    off_ms: "dict[str, float]" = {}
    for i, iv in enumerate(intervals):
        side = on_ms if i in member_set else off_ms
        side[iv.phase] = side.get(iv.phase, 0.0) + iv.dur * 1e3
    waits = classify_waits(intervals)
    for kind, _lane, dur in (explicit_waits or ()):
        if kind in waits:
            waits[kind] += max(0.0, dur)
    crit_ms = crit * 1e3
    out = {
        "critical_path_ms": round(crit_ms, 4),
        "total_work_ms": round(total_work * 1e3, 4),
        "overlap_ratio": round(ratio, 6),
        "intervals": len(intervals),
        "lanes": sorted({iv.lane for iv in intervals}),
        "on_critical_path_ms": {k: round(v, 4)
                                for k, v in sorted(on_ms.items())},
        "off_critical_path_ms": {k: round(v, 4)
                                 for k, v in sorted(off_ms.items())},
        "critical_share": {
            k: round(v / crit_ms, 6) for k, v in sorted(on_ms.items())
        } if crit_ms > 0 else {},
        "waits_ms": {k: round(v * 1e3, 4) for k, v in waits.items()},
    }
    if wall_ms is not None:
        out["wall_ms"] = round(wall_ms, 4)
    return out


# -- the ledger ----------------------------------------------------------------


class CriticalLedger:
    """Bounded ring of per-solve critical analyses + monotone activity
    counters. Fed by GapLedger._observe; read by /debug/criticalz, the
    statusz ``critical`` section, flight-recorder bundles and the
    Perfetto critical lane."""

    #: synthetic pid for the critical lane in merged Perfetto traces,
    #: adjacent to continuous.PROFILE_LANE_PID (0x70F1)
    LANE_PID = 0x70F2

    def __init__(self, ring: "int | None" = None):
        self._lock = threading.Lock()
        self._rows: "deque[dict]" = deque(
            maxlen=ring if ring is not None else _ring_cap())
        self.records_total = 0
        self.intervals_total = 0
        self.wait_notes_total = 0
        self._wait_ms_total: "dict[str, float]" = {w: 0.0 for w in WAITS}

    # -- write side ----------------------------------------------------------

    def observe(self, source: str, intervals: "list[Interval]",
                explicit_waits: "list[tuple[str, str, float]]",
                wall_ms: float, anchor_ts: float) -> "dict | None":
        """Analyze one solve's intervals and file the result. Returns the
        analysis row (the gap ledger embeds a copy in its flat row) or
        None when the plane is disabled or nothing was measured."""
        if not enabled() or not intervals:
            return None
        row = analyze(intervals, explicit_waits, wall_ms=wall_ms)
        row["ts"] = time.time()
        row["source"] = source
        # wall-clock anchor + relative interval records: everything the
        # Perfetto merge needs to place slices without re-deriving time
        row["anchor_ts"] = anchor_ts
        row["records"] = [
            {"lane": iv.lane, "phase": iv.phase,
             "start_ms": round(iv.start * 1e3, 4),
             "end_ms": round(iv.end * 1e3, 4),
             "dur_ms": round(iv.dur * 1e3, 4)}
            for iv in intervals[:64]
        ]
        with self._lock:
            self._rows.append(row)
            self.records_total += 1
            self.intervals_total += len(intervals)
            for k, ms in row["waits_ms"].items():
                self._wait_ms_total[k] = self._wait_ms_total.get(k, 0.0) + ms
        OVERLAP_RATIO.set(row["overlap_ratio"], source=source)
        CRITICAL_PATH_MS.set(row["critical_path_ms"], source=source)
        for k, ms in row["waits_ms"].items():
            if ms > 0:
                WAIT_MS.inc(ms, wait=k)
        return row

    def count_wait_note(self) -> None:
        with self._lock:
            self.wait_notes_total += 1

    # -- read side -----------------------------------------------------------

    def ring_len(self) -> int:
        with self._lock:
            return len(self._rows)

    def rows(self, limit: "int | None" = None) -> "list[dict]":
        with self._lock:
            out = list(self._rows)
        return out[-limit:] if limit else out

    def clear(self) -> None:
        with self._lock:
            self._rows.clear()

    def activity(self) -> dict:
        """Monotone counters + ring length — the chaos
        ``critical-strict-noop`` invariant diffs two of these."""
        with self._lock:
            return {
                "records_total": self.records_total,
                "intervals_total": self.intervals_total,
                "wait_notes_total": self.wait_notes_total,
                "ring": len(self._rows),
            }

    def snapshot(self) -> dict:
        """The statusz schema-11 ``critical`` section (also embedded in
        flight-recorder bundles)."""
        from . import roofline

        with self._lock:
            rows = list(self._rows)
            waits = {k: round(v, 3) for k, v in self._wait_ms_total.items()}
        return {
            "enabled": enabled(),
            "lanes": list(LANES),
            "waits": list(WAITS),
            "records_total": self.records_total,
            "intervals_total": self.intervals_total,
            "wait_notes_total": self.wait_notes_total,
            "ring_len": len(rows),
            "wait_ms_total": waits,
            "last": [{k: v for k, v in r.items() if k != "records"}
                     for r in rows[-3:]],
            "roofline_measured": roofline.measured_snapshot(),
        }

    def criticalz(self, limit: int = 50) -> dict:
        """/debug/criticalz?format=json — the full read surface."""
        from . import roofline

        rows = self.rows(limit)
        return {
            "tool": "karpenter_tpu.criticalz",
            "schema": 1,
            "enabled": enabled(),
            "lanes": list(LANES),
            "waits": list(WAITS),
            "phase_lanes": dict(PHASE_LANES),
            "records_total": self.records_total,
            "ring_len": self.ring_len(),
            "rows": rows,
            "roofline_measured": roofline.measured_snapshot(),
        }

    def merge_chrome(self, doc: dict) -> dict:
        """Append a ``critical`` process lane to a chrome-trace doc: one
        complete-slice per interval record (args mark critical-path
        membership) plus instant markers for the classified waits —
        the fleetview/profiling process-lane idiom, pid 0x70F2."""
        if not enabled() or not isinstance(doc, dict):
            return doc
        events = doc.get("traceEvents")
        if not isinstance(events, list) or not events:
            return doc
        spans = [e for e in events if e.get("ph") != "M"]
        if not spans:
            return doc
        lo = min(e["ts"] for e in spans)
        hi = max(e["ts"] + e.get("dur", 0) for e in spans)
        lane_events: "list[dict]" = []
        tid_of = {lane: i for i, lane in enumerate(LANES)}
        for row in self.rows():
            anchor_us = row.get("anchor_ts", 0.0) * 1e6
            if anchor_us < lo - 1e6 or anchor_us > hi:
                continue
            on_crit = row.get("critical_share", {})
            for rec in row.get("records", ()):
                ts = anchor_us + rec["start_ms"] * 1e3
                if ts < lo or ts > hi:
                    continue
                lane_events.append({
                    "name": rec["phase"], "ph": "X",
                    "ts": ts, "dur": max(rec["dur_ms"], 1e-3) * 1e3,
                    "pid": self.LANE_PID,
                    "tid": tid_of.get(rec["lane"], len(LANES)),
                    "args": {"lane": rec["lane"],
                             "on_critical_path": rec["phase"] in on_crit,
                             "source": row.get("source", "")},
                })
            for kind, ms in row.get("waits_ms", {}).items():
                if ms <= 0 or anchor_us < lo or anchor_us > hi:
                    continue
                lane_events.append({
                    "name": kind, "ph": "i", "s": "t",
                    "ts": anchor_us, "pid": self.LANE_PID,
                    "tid": len(LANES),
                    "args": {"wait_ms": ms, "source": row.get("source", "")},
                })
        if not lane_events:
            return doc
        meta = [e for e in events if e.get("ph") == "M"]
        rest = [e for e in events if e.get("ph") != "M"] + lane_events
        rest.sort(key=lambda e: e["ts"])
        meta.append({"name": "process_name", "ph": "M",
                     "pid": self.LANE_PID, "tid": 0,
                     "args": {"name": "critical"}})
        for lane, tid in tid_of.items():
            meta.append({"name": "thread_name", "ph": "M",
                         "pid": self.LANE_PID, "tid": tid,
                         "args": {"name": f"lane:{lane}"}})
        meta.append({"name": "thread_name", "ph": "M",
                     "pid": self.LANE_PID, "tid": len(LANES),
                     "args": {"name": "waits"}})
        doc = dict(doc)
        doc["traceEvents"] = meta + rest
        return doc


CRITICAL = CriticalLedger()


def activity() -> dict:
    return CRITICAL.activity()


def snapshot() -> dict:
    return CRITICAL.snapshot()


def criticalz(limit: int = 50) -> dict:
    return CRITICAL.criticalz(limit)


def merge_chrome(doc: dict) -> dict:
    return CRITICAL.merge_chrome(doc)
