"""Device/host attribution profiling plane (ISSUE 13).

Three instruments behind one advisory switch (state.enabled):

- continuous.PROFILER — always-on sampling host profiler + device-event
  backend ladder (tpu-sync -> cpu-synthetic), bounded rings, served at
  /debug/profilez and merged into Perfetto exports as a ``profiling``
  process lane.
- gapledger.GAP_LEDGER — per-solve wall-time decomposition into
  encode/serialize/link/device_exec/decode with an explicit
  ``unaccounted`` residue metric.
- roofline — BucketPlan-rung cost model giving the theoretical floor the
  measured device phase is compared against.

``make profile-drill`` (benchmarks/profile_drill.py) is the recorded
proof: >=95% of a 10k-pod solve's wall attributed, residue <5%, profiler
overhead <5% vs a disabled baseline, on both routing paths.
"""
from __future__ import annotations

from . import critical  # noqa: F401
from .continuous import PROFILE_LANE_PID, PROFILER  # noqa: F401
from .critical import CRITICAL, LANES, WAITS  # noqa: F401
from .gapledger import GAP_LEDGER, PHASE_NAMES, PHASES  # noqa: F401
from .state import disabled, enabled, set_enabled  # noqa: F401


def activity() -> dict:
    """Monotonic activity counters + ring lengths — the chaos
    ``profiling-strict-noop`` invariant diffs two of these."""
    return {
        "host_samples": PROFILER.host.samples_total,
        "host_ring": PROFILER.host.ring_len(),
        "device_events": PROFILER.device.events_total,
        "device_ring": PROFILER.device.ring_len(),
        "gap_rows": GAP_LEDGER.rows_total,
        "gap_ring": GAP_LEDGER.ring_len(),
    }


def snapshot() -> dict:
    """The statusz schema-7 ``profiling`` section (also bundled by the
    flight recorder)."""
    return {
        "enabled": enabled(),
        "host": PROFILER.host.snapshot(),
        "device": PROFILER.device.snapshot(),
        "gap": GAP_LEDGER.snapshot(),
    }


def profilez(limit: int = 100) -> dict:
    """pprof-style aggregation served at /debug/profilez?format=json."""
    folded = PROFILER.host.folded(limit)
    return {
        "tool": "karpenter_tpu.profilez",
        "schema": 1,
        "enabled": enabled(),
        "sample_type": {"type": "samples", "unit": "count"},
        "period_ms": round(1e3 / PROFILER.host.hz, 3),
        "host": PROFILER.host.snapshot(),
        "stacks": [
            {"frames": stack.split(";"), "count": count}
            for stack, count in folded
        ],
        "device": PROFILER.device.snapshot(),
        "gap": GAP_LEDGER.snapshot(),
    }


def folded_text(limit: "int | None" = None) -> str:
    """Flamegraph-ready folded stacks (/debug/profilez?format=folded —
    pipe straight into flamegraph.pl / speedscope)."""
    return "\n".join(
        f"{stack} {count}" for stack, count in PROFILER.host.folded(limit))


def merge_chrome(doc: dict) -> dict:
    """Append the ``profiling`` process lane to a chrome-trace doc, then
    the ``critical`` lane (interval records with critical-path marks +
    wait markers) when that plane has evidence in the window."""
    return critical.merge_chrome(PROFILER.merge_chrome(doc))
