"""Always-on continuous profiler: sampling host profiler + device ladder.

Host half: a daemon thread walks ``sys._current_frames()`` at a low rate
(default 19 Hz — deliberately prime so it can't phase-lock with a
controller cycle) and folds each thread's stack into the
``root;frame;...;leaf`` form flamegraph tooling eats directly. Samples
land in a bounded ring (``KARPENTER_TPU_PROFILE_RING``); aggregation to
pprof-style JSON happens at read time (/debug/profilez), never on the
sampling path. The sampler measures ITSELF — cumulative sweep cost over
elapsed wall feeds ``karpenter_profile_overhead_ratio``, so the <5%
overhead claim in the profile drill is the profiler's own number checked
against an enabled-vs-disabled wall-clock A/B.

Device half: a backend ladder in the ShardedContext advisory style. On a
real TPU backend the blocking fetch in ``_solve_once`` is a device sync,
so its wall time IS the device-exec measurement ("tpu-sync" rung), and
``jax.profiler`` trace capture is available as a guarded passthrough for
deep dives. On the CPU backend the same perf_counter interval is recorded
as a synthetic timer ("cpu-synthetic" rung) — identical math, honestly
labelled. Nothing here is load-bearing: every rung degrades to a no-op
and never raises into the solve path.
"""
from __future__ import annotations

import logging
import os
import sys
import threading
import time
from collections import Counter as _TallyCounter
from collections import deque

from ..metrics import REGISTRY
from . import state

log = logging.getLogger(__name__)

HZ_ENV = "KARPENTER_TPU_PROFILE_HZ"
RING_ENV = "KARPENTER_TPU_PROFILE_RING"
DEFAULT_HZ = 19.0
DEFAULT_RING = 4096
DEVICE_RING = 1024
MAX_STACK_DEPTH = 64

#: synthetic pid for the profiling lane in merged Perfetto traces — far
#: outside the replica pids fleetview assigns (0..replicas) and stable
#: across processes so lanes from bundles diff cleanly.
PROFILE_LANE_PID = 0x70F1

OVERHEAD_RATIO = REGISTRY.gauge(
    "karpenter_profile_overhead_ratio",
    "Sampler self-cost: cumulative sweep seconds / elapsed wall seconds",
    ())
HOST_SAMPLES = REGISTRY.counter(
    "karpenter_profile_host_samples_total",
    "Host stack samples captured by the continuous profiler",
    ())
DEVICE_EVENTS = REGISTRY.counter(
    "karpenter_profile_device_events_total",
    "Device-exec events recorded through the backend ladder",
    ("mode",))


def _env_pos(env: str, fallback: float, lo: float, hi: float) -> float:
    raw = os.environ.get(env)
    if raw is None:
        return fallback
    try:
        v = float(raw)
        if v <= 0:
            raise ValueError(raw)
    except ValueError:
        log.warning("%s=%r invalid (want a positive number); using %s",
                    env, raw, fallback)
        return fallback
    return min(max(v, lo), hi)


def detect_backend() -> str:
    """Best-effort jax backend name; 'cpu' when jax is absent or unhappy.
    Advisory — never imports jax eagerly at module import."""
    try:
        import jax
        return str(jax.default_backend())
    except Exception:  # noqa: BLE001 — ladder degrades, never raises
        return "cpu"


def _fold(frame) -> str:
    """frame chain -> 'root;...;leaf' (module.qualname per frame)."""
    parts: "list[str]" = []
    depth = 0
    while frame is not None and depth < MAX_STACK_DEPTH:
        code = frame.f_code
        mod = frame.f_globals.get("__name__", "?")
        name = getattr(code, "co_qualname", code.co_name)
        parts.append(f"{mod}.{name}")
        frame = frame.f_back
        depth += 1
    parts.reverse()
    return ";".join(parts)


class HostSampler:
    """sys._current_frames() wall-clock sampler with bounded ring."""

    def __init__(self, hz: "float | None" = None,
                 ring: "int | None" = None):
        self.hz = hz if hz is not None else _env_pos(
            HZ_ENV, DEFAULT_HZ, 1.0, 1000.0)
        cap = ring if ring is not None else int(_env_pos(
            RING_ENV, DEFAULT_RING, 64, 262144))
        self._ring: "deque[tuple[float, str, str]]" = deque(maxlen=cap)
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: "threading.Thread | None" = None
        self.samples_total = 0
        self.sample_cost_s = 0.0
        self._started_at: "float | None" = None
        self._atexit_registered = False

    # -- lifecycle -----------------------------------------------------------

    def ensure_started(self) -> bool:
        """Idempotent lazy start (first solve / first profilez read).
        Refuses while the plane is disabled — strict-noop."""
        if not state.enabled():
            return False
        with self._lock:
            if self._thread is not None and self._thread.is_alive():
                return True
            self._stop.clear()
            self._started_at = time.monotonic()
            self._thread = threading.Thread(
                target=self._run, name="profiling-sampler", daemon=True)
            self._thread.start()
            if not self._atexit_registered:
                # join the sampler before interpreter teardown: a daemon
                # thread walking sys._current_frames() while the runtime
                # (and XLA's C++ threadpools) shut down is a crash race
                import atexit

                atexit.register(self.stop)
                self._atexit_registered = True
            return True

    def stop(self) -> None:
        with self._lock:
            t = self._thread
            self._thread = None
        self._stop.set()
        if t is not None and t.is_alive():
            t.join(timeout=2.0)

    def running(self) -> bool:
        t = self._thread
        return t is not None and t.is_alive()

    def _run(self) -> None:
        own = threading.get_ident()
        interval = 1.0 / self.hz
        while not self._stop.wait(interval):
            if not state.enabled():
                continue  # disabled mid-flight: idle, sample nothing
            t0 = time.perf_counter()
            try:
                frames = sys._current_frames()
                names = {t.ident: t.name for t in threading.enumerate()}
                now = time.time()
                n = 0
                with self._lock:
                    for tid, frame in frames.items():
                        if tid == own:
                            continue
                        self._ring.append((
                            now, names.get(tid, f"tid-{tid}"), _fold(frame)))
                        n += 1
                    self.samples_total += n
            except Exception:  # noqa: BLE001 — advisory, never crash
                continue
            cost = time.perf_counter() - t0
            with self._lock:
                self.sample_cost_s += cost
            HOST_SAMPLES.inc(n)
            OVERHEAD_RATIO.set(self.overhead_ratio())

    # -- reads ---------------------------------------------------------------

    def ring_len(self) -> int:
        with self._lock:
            return len(self._ring)

    def samples(self) -> "list[tuple[float, str, str]]":
        with self._lock:
            return list(self._ring)

    def overhead_ratio(self) -> float:
        if self._started_at is None:
            return 0.0
        elapsed = time.monotonic() - self._started_at
        return self.sample_cost_s / elapsed if elapsed > 0 else 0.0

    def folded(self, limit: "int | None" = None) -> "list[tuple[str, int]]":
        tally: "_TallyCounter[str]" = _TallyCounter()
        for _ts, _thread, stack in self.samples():
            tally[stack] += 1
        out = tally.most_common(limit)
        return out

    def snapshot(self) -> dict:
        return {
            "running": self.running(),
            "hz": self.hz,
            "ring_len": self.ring_len(),
            "ring_cap": self._ring.maxlen,
            "samples_total": self.samples_total,
            "overhead_ratio": round(self.overhead_ratio(), 6),
        }


class DeviceEventLadder:
    """Backend ladder for device-exec evidence: tpu-sync -> cpu-synthetic."""

    def __init__(self):
        self._lock = threading.Lock()
        self._ring: "deque[dict]" = deque(maxlen=DEVICE_RING)
        self.events_total = 0
        self._backend: "str | None" = None
        self._trace_active = False

    def mode(self) -> str:
        if self._backend is None:
            self._backend = detect_backend()
        return "tpu-sync" if self._backend == "tpu" else "cpu-synthetic"

    def observe(self, seconds: float, *, bucket: str = "",
                route: str = "single") -> None:
        if not state.enabled():
            return
        mode = self.mode()
        with self._lock:
            self._ring.append({
                "ts": time.time(),
                "ms": round(max(0.0, seconds) * 1e3, 4),
                "bucket": bucket,
                "route": route,
                "mode": mode,
            })
            self.events_total += 1
        DEVICE_EVENTS.inc(mode=mode)

    def ring_len(self) -> int:
        with self._lock:
            return len(self._ring)

    def events(self) -> "list[dict]":
        with self._lock:
            return list(self._ring)

    # guarded jax.profiler passthrough for deep dives (profile drill on a
    # real chip) — single-flight like the service trace_every capture
    def start_trace(self, logdir: str) -> bool:
        if not state.enabled() or self.mode() != "tpu-sync":
            return False
        with self._lock:
            if self._trace_active:
                return False
            self._trace_active = True
        try:
            import jax
            jax.profiler.start_trace(logdir)
            return True
        except Exception:  # noqa: BLE001
            with self._lock:
                self._trace_active = False
            return False

    def stop_trace(self) -> None:
        with self._lock:
            if not self._trace_active:
                return
            self._trace_active = False
        try:
            import jax
            jax.profiler.stop_trace()
        except Exception:  # noqa: BLE001
            pass

    def snapshot(self) -> dict:
        ev = self.events()
        return {
            "mode": self.mode(),
            "events_total": self.events_total,
            "ring_len": len(ev),
            "last": ev[-3:],
        }


class ContinuousProfiler:
    """Facade owning the host sampler and the device ladder."""

    def __init__(self):
        self.host = HostSampler()
        self.device = DeviceEventLadder()

    def ensure_started(self) -> bool:
        return self.host.ensure_started()

    def stop(self) -> None:
        self.host.stop()

    def merge_chrome(self, doc: dict) -> dict:
        """Append the profiling process lane to a Perfetto/chrome-trace doc
        (the fleetview process-lane idiom: distinct pid + process_name
        metadata, instant events per host sample inside the trace's time
        window). Returns the doc unchanged when profiling is disabled or
        the doc carries no span events."""
        if not state.enabled() or not isinstance(doc, dict):
            return doc
        events = doc.get("traceEvents")
        if not isinstance(events, list) or not events:
            return doc
        spans = [e for e in events if e.get("ph") != "M"]
        if not spans:
            return doc
        lo = min(e["ts"] for e in spans)
        hi = max(e["ts"] + e.get("dur", 0) for e in spans)
        lane: "list[dict]" = []
        for ts, thread, stack in self.host.samples():
            ts_us = ts * 1e6
            if ts_us < lo or ts_us > hi:
                continue
            leaf = stack.rsplit(";", 1)[-1]
            lane.append({
                "name": leaf, "ph": "i", "s": "t",
                "ts": ts_us, "pid": PROFILE_LANE_PID,
                "tid": hash(thread) % 1000,
                "args": {"stack": stack, "thread": thread},
            })
        for ev in self.device.events():
            ts_us = ev["ts"] * 1e6
            if ts_us < lo or ts_us > hi:
                continue
            lane.append({
                "name": f"device_exec[{ev['mode']}]", "ph": "X",
                "ts": ts_us - ev["ms"] * 1e3, "dur": ev["ms"] * 1e3,
                "pid": PROFILE_LANE_PID, "tid": 0,
                "args": {"bucket": ev["bucket"], "route": ev["route"]},
            })
        if not lane:
            return doc
        meta = [e for e in events if e.get("ph") == "M"]
        rest = [e for e in events if e.get("ph") != "M"] + lane
        rest.sort(key=lambda e: e["ts"])
        meta.append({"name": "process_name", "ph": "M",
                     "pid": PROFILE_LANE_PID, "tid": 0,
                     "args": {"name": "profiling"}})
        doc = dict(doc)
        doc["traceEvents"] = meta + rest
        return doc


PROFILER = ContinuousProfiler()
