"""Gap ledger: per-solve wall-time decomposition with an explicit residue.

The motivating gap (ISSUE 13 / ROADMAP item 1): the recorded device trace
shows device-exec at 0.644 ms/run while the on-chip headline was
129.1 ms, and nothing could say where the other ~128 ms went. The ledger
closes that hole by accounting, not by guessing: an OUTER wall-time scope
(service RPC body, or TPUSolver.solve for in-process callers) brackets
the whole solve, INNER layers file what they measured into named phases,
and whatever the phases don't cover is published — loudly — as
``unaccounted``. A residue near zero makes the headline decomposition
trustworthy; a growing residue is itself the finding.

The scope is the hbm_scope idiom from solver/buckets.py: thread-local,
outermost-opener-wins, so the service scope subsumes the solver scope
which subsumes both rounds of the two-round driver — nested layers just
accumulate notes into the one open record.

Phase table (the ONLY phase vocabulary; hack/check_phase_accounting.py
asserts every backing span name below exists in the Tracer phase
registry):

    encode       host problem encoding (solver.encode)
    serialize    wire decode + response encode at the service boundary
    link         host dispatch / XLA link+compile (solver.dispatch.*)
    device_exec  the one blocking device->host fetch (solver.transfer)
    decode       host result shaping (solver.decode)

Shares always sum to exactly 1: with ``total = max(wall, Σphases)``,
``unaccounted = max(0, wall − Σphases)`` and both shares divide by
``total`` — residue can never go negative even under clock skew.
"""
from __future__ import annotations

import contextlib
import logging
import os
import threading
import time
from collections import deque

from ..metrics import REGISTRY
from . import critical, roofline, state

log = logging.getLogger(__name__)

#: gap phase -> backing Tracer span names. Order is presentation order in
#: statusz / profilez / the drill artifact.
PHASES = (
    ("extract", ("solver.extract",)),
    ("warm_start", ("solver.warm_start",)),
    ("encode", ("solver.encode",)),
    ("serialize", ("solver.serialize",)),
    ("link", ("solver.dispatch.execute", "solver.dispatch.compile")),
    ("device_exec", ("solver.transfer",)),
    ("decode", ("solver.decode",)),
)
PHASE_NAMES = tuple(name for name, _spans in PHASES)

RING_ENV = "KARPENTER_TPU_PROFILE_GAP_RING"
DEFAULT_RING = 512

PHASE_MS = REGISTRY.counter(
    "karpenter_profile_phase_ms_total",
    "Cumulative per-phase solve milliseconds (phase=unaccounted is the residue)",
    ("phase",))
GAP_SOLVES = REGISTRY.counter(
    "karpenter_profile_solves_total",
    "Solves observed by the gap ledger",
    ("source",))
UNACCOUNTED_SHARE = REGISTRY.gauge(
    "karpenter_profile_unaccounted_share",
    "Unaccounted share of the most recent solve's wall time",
    ("source",))


def _ring_cap() -> int:
    raw = os.environ.get(RING_ENV)
    if raw is None:
        return DEFAULT_RING
    try:
        v = int(raw)
        if v <= 0:
            raise ValueError(raw)
        return min(v, 65536)
    except ValueError:
        log.warning("%s=%r invalid (want a positive integer); using %d",
                    RING_ENV, raw, DEFAULT_RING)
        return DEFAULT_RING


class _Record:
    __slots__ = ("phases", "attrs", "intervals", "waits", "wall0", "perf0")

    def __init__(self):
        self.phases: "dict[str, float]" = {}
        self.attrs: "dict[str, object]" = {}
        # the critical-plane side of the record: interval records per
        # note, explicit cross-thread wait notes, and the wall/monotonic
        # anchor pair (wall places Perfetto slices; perf positions the
        # relative interval times)
        self.intervals: "list[critical.Interval]" = []
        self.waits: "list[tuple[str, str, float]]" = []
        self.wall0 = time.time()
        self.perf0 = time.perf_counter()


class GapLedger:
    def __init__(self, ring: "int | None" = None):
        self._tls = threading.local()
        self._lock = threading.Lock()
        self._rows: "deque[dict]" = deque(
            maxlen=ring if ring is not None else _ring_cap())
        self.rows_total = 0
        self._phase_ms_total: "dict[str, float]" = {}

    # -- write side ----------------------------------------------------------

    @contextlib.contextmanager
    def solve_scope(self, source: str):
        """Outermost-opener-wins wall bracket (hbm_scope idiom). Nested
        opens are transparent: they yield the already-open record so inner
        layers keep accumulating into the outer wall measurement."""
        if not state.enabled():
            yield None
            return
        cur = getattr(self._tls, "rec", None)
        if cur is not None:
            yield cur
            return
        # the always-on part of "always-on": the first profiled solve lazily
        # starts the host sampler (idempotent; refuses while disabled)
        from . import PROFILER

        PROFILER.ensure_started()
        rec = _Record()
        self._tls.rec = rec
        t0 = time.perf_counter()
        try:
            yield rec
        finally:
            self._tls.rec = None
            self._observe(source, time.perf_counter() - t0, rec)

    def note(self, phase: str, seconds: float, *,
             lane: "str | None" = None,
             end_pc: "float | None" = None) -> None:
        """File measured seconds into a named phase of the open record.
        No-op without an open scope (a bare encode_problem in a test) or
        while the plane is disabled.

        The flat accumulation below is the ORIGINAL ledger semantics,
        byte-for-byte — the critical plane rides along as an ADDITIONAL
        interval record (lane + monotonic start/end), so the flat view
        stays a bit-compatible projection of the interval records
        (critical.project_flat; tests assert equality).

        ``lane`` overrides the phase's default lane
        (critical.PHASE_LANES); ``end_pc`` is the perf_counter timestamp
        the measured span ENDED at (defaults to now) — call sites that
        batch several notes after the fact pass their own phase-boundary
        timestamps so the intervals don't artificially stack."""
        rec = getattr(self._tls, "rec", None)
        if rec is None or not state.enabled():
            return
        if phase not in PHASE_NAMES:
            raise ValueError(
                f"unknown gap phase {phase!r} (want one of {PHASE_NAMES})")
        rec.phases[phase] = rec.phases.get(phase, 0.0) + max(0.0, seconds)
        if (critical.enabled()
                and len(rec.intervals) < critical.MAX_INTERVALS_PER_SOLVE):
            if lane is not None and lane not in critical.LANES:
                raise ValueError(
                    f"unknown lane {lane!r} (want one of {critical.LANES})")
            end = (end_pc if end_pc is not None
                   else time.perf_counter()) - rec.perf0
            rec.intervals.append(critical.make_interval(
                lane or critical.PHASE_LANES.get(phase, "solver"),
                phase, end, seconds))

    def note_wait(self, kind: str, seconds: float, *,
                  lane: str = "tick") -> None:
        """File an EXPLICIT wait (critical.WAITS vocabulary) against the
        open record — the cross-thread waits lane geometry cannot see,
        e.g. the fleet frontend's admission->dispatch queue time. No-op
        without an open scope or while either plane is disabled."""
        rec = getattr(self._tls, "rec", None)
        if rec is None or not state.enabled() or not critical.enabled():
            return
        if kind not in critical.WAITS:
            raise ValueError(
                f"unknown wait {kind!r} (want one of {critical.WAITS})")
        if lane not in critical.LANES:
            raise ValueError(
                f"unknown lane {lane!r} (want one of {critical.LANES})")
        rec.waits.append((kind, lane, max(0.0, seconds)))
        critical.CRITICAL.count_wait_note()

    def annotate(self, **attrs) -> None:
        """Attach rung/route metadata to the open record (bucket label,
        rung dims for the roofline, routing, device_count)."""
        rec = getattr(self._tls, "rec", None)
        if rec is None or not state.enabled():
            return
        rec.attrs.update(attrs)

    # -- observe -------------------------------------------------------------

    def _observe(self, source: str, wall_s: float, rec: _Record) -> None:
        if not rec.phases:
            return  # nothing was measured (native solver, error path)
        phases_ms = {k: v * 1e3 for k, v in rec.phases.items()}
        attributed = sum(phases_ms.values())
        wall_ms = wall_s * 1e3
        total = max(wall_ms, attributed, 1e-9)
        unaccounted = max(0.0, wall_ms - attributed)
        row = {
            "ts": time.time(),
            "source": source,
            "wall_ms": round(wall_ms, 4),
            "phases_ms": {k: round(v, 4) for k, v in phases_ms.items()},
            "attributed_ms": round(attributed, 4),
            "unaccounted_ms": round(unaccounted, 4),
            "attributed_share": round(attributed / total, 6),
            "unaccounted_share": round(unaccounted / total, 6),
        }
        for key in ("bucket", "route", "device_count", "batch"):
            if key in rec.attrs:
                row[key] = rec.attrs[key]
        device_ms = phases_ms.get("device_exec", 0.0)
        rf = self._roofline_for(rec)
        if rf is not None:
            row["roofline"] = {
                "bytes_moved": rf.bytes_moved,
                "flops": rf.flops,
                "floor_ms": round(rf.floor_ms, 6),
                "backend": rf.backend,
                "ratio": round(roofline.observe(rf, device_ms), 3),
            }
        # hand the interval records to the critical plane — the row grows
        # a `critical` subsection (chain, overlap ratio, waits) but every
        # pre-existing key above is computed exactly as before
        crit_row = critical.CRITICAL.observe(
            source, rec.intervals, rec.waits, wall_ms, rec.wall0)
        if crit_row is not None:
            row["critical"] = {
                k: crit_row[k]
                for k in ("critical_path_ms", "total_work_ms",
                          "overlap_ratio", "critical_share", "waits_ms",
                          "on_critical_path_ms", "off_critical_path_ms")
            }
        if device_ms > 0:
            from .continuous import PROFILER
            PROFILER.device.observe(
                device_ms / 1e3,
                bucket=str(rec.attrs.get("bucket", "")),
                route=str(rec.attrs.get("route", "single")))
        with self._lock:
            self._rows.append(row)
            self.rows_total += 1
            for k, v in phases_ms.items():
                self._phase_ms_total[k] = self._phase_ms_total.get(k, 0) + v
            self._phase_ms_total["unaccounted"] = (
                self._phase_ms_total.get("unaccounted", 0) + unaccounted)
        for k, v in phases_ms.items():
            PHASE_MS.inc(v, phase=k)
        PHASE_MS.inc(unaccounted, phase="unaccounted")
        GAP_SOLVES.inc(source=source)
        UNACCOUNTED_SHARE.set(row["unaccounted_share"], source=source)

    def _roofline_for(self, rec: _Record):
        a = rec.attrs
        if "groups" not in a or "slots" not in a:
            return None
        try:
            return roofline.estimate(
                a["groups"], a["slots"], a.get("existing", 0),
                pv=a.get("pv", 1), t=a.get("t", 16), s=a.get("s", 4),
                device_count=a.get("device_count", 1),
                backend=a.get("backend", "cpu"),
                bucket=str(a.get("bucket", "")))
        except Exception:  # noqa: BLE001 — advisory
            return None

    # -- read side -----------------------------------------------------------

    def ring_len(self) -> int:
        with self._lock:
            return len(self._rows)

    def rows(self, limit: "int | None" = None) -> "list[dict]":
        with self._lock:
            out = list(self._rows)
        return out[-limit:] if limit else out

    def clear(self) -> None:
        with self._lock:
            self._rows.clear()

    def snapshot(self) -> dict:
        with self._lock:
            rows = list(self._rows)
            totals = dict(self._phase_ms_total)
        grand = sum(totals.values())
        return {
            "phases": list(PHASE_NAMES),
            "rows_total": self.rows_total,
            "ring_len": len(rows),
            "phase_ms_total": {k: round(v, 3) for k, v in totals.items()},
            "phase_share": {
                k: round(v / grand, 4) for k, v in totals.items()
            } if grand > 0 else {},
            "last": rows[-5:],
        }


GAP_LEDGER = GapLedger()
