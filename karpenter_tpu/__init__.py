"""karpenter-tpu: a TPU-native Kubernetes node-provisioning autoscaler.

Public surface (see docs/getting-started.md):

- ``karpenter_tpu.operator.Operator`` — the controller plane.
- ``karpenter_tpu.solver.core.TPUSolver`` / ``NativeSolver`` — the batched
  scheduling backends (bit-parity with ``oracle.scheduler``).
- ``python -m karpenter_tpu`` — controller / solver-serve / cleanup CLIs.
"""

__version__ = "0.1.0"
