"""Instance types, offerings, and the offering algebra.

Parity targets:
- `cloudprovider.InstanceType{Name, Requirements, Offerings, Capacity,
  Overhead}.Allocatable()` — /root/reference/pkg/cloudprovider/instancetype.go:50-65
  and consumption at cloudprovider.go:352-363.
- `cloudprovider.Offering{Zone, CapacityType, Price, Available}` with
  `Offerings.Available().Requirements(reqs).Cheapest()` —
  instancetypes.go:133-161, instance.go:445-462.
- Capacity/overhead computation (vmMemoryOverheadPercent, kubeReserved CPU
  curve, eviction threshold, ENI-limited pod density) —
  instancetype.go:128-163, 229-319. Re-derived, not copied: see
  karpenter_tpu/providers/instancetypes.py for the generator.
"""

from __future__ import annotations

import dataclasses
from typing import Iterable, Optional

from ..apis import wellknown as wk
from .requirements import Requirement, Requirements


@dataclasses.dataclass(frozen=True)
class Offering:
    zone: str
    capacity_type: str  # "spot" | "on-demand"
    price: float
    available: bool = True


class Offerings(tuple):
    """Ordered offering collection with the reference's filter/select algebra."""

    def available(self) -> "Offerings":
        return Offerings(o for o in self if o.available)

    def requirements(self, reqs: Requirements) -> "Offerings":
        """Filter by zone/capacity-type requirements (instance.go:445-462)."""
        zone_req = reqs.get(wk.LABEL_ZONE)
        ct_req = reqs.get(wk.LABEL_CAPACITY_TYPE)
        out = []
        for o in self:
            if zone_req is not None and not zone_req.has(o.zone):
                continue
            if ct_req is not None and not ct_req.has(o.capacity_type):
                continue
            out.append(o)
        return Offerings(out)

    def cheapest(self) -> Optional[Offering]:
        return min(self, key=lambda o: o.price, default=None)

    def has(self, zone: str, capacity_type: str) -> bool:
        return any(o.zone == zone and o.capacity_type == capacity_type for o in self)


@dataclasses.dataclass(frozen=True)
class InstanceType:
    name: str
    labels: "tuple[tuple[str, str], ...]"  # well-known labels, concrete values
    capacity: "tuple[tuple[str, int], ...]"  # canonical units (cpu millis, mem bytes, counts)
    overhead: "tuple[tuple[str, int], ...]" = ()
    offerings: Offerings = Offerings()

    def labels_dict(self) -> "dict[str, str]":
        return dict(self.labels)

    def requirements(self) -> Requirements:
        """Single-valued In requirements from labels + multi-valued zone /
        capacity-type from offerings (instancetype.go:67-117)."""
        reqs = Requirements.from_labels(
            {k: v for k, v in self.labels if k not in (wk.LABEL_ZONE, wk.LABEL_CAPACITY_TYPE)}
        )
        # zone/capacity-type sets come from AVAILABLE offerings only, matching
        # the reference (unavailable offerings are filtered before requirements
        # are consulted, instancetypes.go:133-161).
        zones = sorted({o.zone for o in self.offerings.available()})
        cts = sorted({o.capacity_type for o in self.offerings.available()})
        if zones:
            reqs.add(Requirement.create(wk.LABEL_ZONE, "In", zones))
        if cts:
            reqs.add(Requirement.create(wk.LABEL_CAPACITY_TYPE, "In", cts))
        return reqs

    def allocatable_vector(self) -> "list[int]":
        """capacity - overhead on the canonical resource axis
        (InstanceType.Allocatable(), cloudprovider.go:352-363)."""
        cap = wk.capacity_vector(dict(self.capacity))
        ovh = wk.capacity_vector(dict(self.overhead))
        return [max(0, c - o) for c, o in zip(cap, ovh)]

    def cheapest_price(self, reqs: Requirements) -> float:
        off = self.offerings.available().requirements(reqs).cheapest()
        return off.price if off is not None else float("inf")


@dataclasses.dataclass
class Catalog:
    """The full instance-type universe for one solve (device-resident on TPU).

    Versioned with a seqnum like the reference's instance-type cache
    (instancetypes.go:62-68): any mutation bumps `seqnum`, invalidating
    device-side encodings.
    """

    types: "list[InstanceType]"
    seqnum: int = 0

    def __post_init__(self):
        self.by_name = {t.name: t for t in self.types}

    def bump(self):
        """Mutation barrier: bump the version AND rebuild derived indexes so
        callers can't observe a stale by_name after appending types."""
        self.seqnum += 1
        self.by_name = {t.name: t for t in self.types}

    def filter_compatible(self, reqs: Requirements) -> "list[InstanceType]":
        """requirements-compatible ∧ offerings-available filter
        (cloudprovider.go:315-321 resolveInstanceTypes)."""
        out = []
        for t in self.types:
            if not t.offerings.available().requirements(reqs):
                continue
            if not reqs.matches_labels(self._schedulable_labels(t, reqs)):
                continue
            out.append(t)
        return out

    @staticmethod
    def _schedulable_labels(t: InstanceType, reqs: Requirements) -> "dict[str, str]":
        """Labels view where zone/capacity-type take any offered value that the
        requirements accept (multi-valued keys resolved against offerings)."""
        labels = t.labels_dict()
        zone_req = reqs.get(wk.LABEL_ZONE)
        ct_req = reqs.get(wk.LABEL_CAPACITY_TYPE)
        for o in t.offerings:
            if not o.available:
                continue
            if zone_req is not None and not zone_req.has(o.zone):
                continue
            if ct_req is not None and not ct_req.has(o.capacity_type):
                continue
            labels[wk.LABEL_ZONE] = o.zone
            labels[wk.LABEL_CAPACITY_TYPE] = o.capacity_type
            return labels
        # no offering satisfies; leave first offering's values so match fails
        if t.offerings:
            labels[wk.LABEL_ZONE] = t.offerings[0].zone
            labels[wk.LABEL_CAPACITY_TYPE] = t.offerings[0].capacity_type
        return labels


def make_instance_type(
    name: str,
    cpu: "str | int",
    memory: "str | int",
    arch: str = "amd64",
    os: str = "linux",
    pods: int = 110,
    zones: Iterable[str] = ("zone-1a", "zone-1b", "zone-1c"),
    od_price: float = 1.0,
    spot_price: "Optional[float]" = None,
    extended: "Optional[dict[str, int]]" = None,
    extra_labels: "Optional[dict[str, str]]" = None,
    overhead_cpu: "str | int" = "0",
    overhead_memory: "str | int" = "0",
) -> InstanceType:
    """Test/fixture constructor (reference analogue: fake instance-type fixtures,
    pkg/fake/zz_generated.describe_instance_types.go)."""
    from ..utils.quantity import cpu_millis, mem_bytes

    family, _, size = name.partition(".")
    cap = {
        wk.RESOURCE_CPU: cpu_millis(cpu),
        wk.RESOURCE_MEMORY: mem_bytes(memory),
        wk.RESOURCE_PODS: pods,
        wk.RESOURCE_EPHEMERAL: mem_bytes("20Gi"),
    }
    for k, v in (extended or {}).items():
        cap[k] = v
    labels = {
        wk.LABEL_INSTANCE_TYPE: name,
        wk.LABEL_ARCH: arch,
        wk.LABEL_OS: os,
        wk.LABEL_INSTANCE_FAMILY: family,
        wk.LABEL_INSTANCE_SIZE: size or "std",
        wk.LABEL_INSTANCE_CPU: str(cpu_millis(cpu) // 1000),
        wk.LABEL_INSTANCE_MEMORY: str(mem_bytes(memory) // (2**20)),
        wk.LABEL_INSTANCE_PODS: str(pods),
    }
    labels.update(extra_labels or {})
    offerings = []
    for z in zones:
        offerings.append(Offering(zone=z, capacity_type=wk.CAPACITY_TYPE_ON_DEMAND, price=od_price))
        if spot_price is not None:
            offerings.append(Offering(zone=z, capacity_type=wk.CAPACITY_TYPE_SPOT, price=spot_price))
    overhead = {
        wk.RESOURCE_CPU: cpu_millis(overhead_cpu),
        wk.RESOURCE_MEMORY: mem_bytes(overhead_memory),
    }
    return InstanceType(
        name=name,
        labels=tuple(sorted(labels.items())),
        capacity=tuple(sorted(cap.items())),
        overhead=tuple(sorted(overhead.items())),
        offerings=Offerings(offerings),
    )
