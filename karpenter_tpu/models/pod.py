"""Pod scheduling model.

Parity target: the pod-side inputs the reference's scheduler consumes —
resource requests, node selectors / required node affinity, tolerations,
topology spread constraints, priority, `controller.kubernetes.io/pod-deletion-cost`
and `karpenter.sh/do-not-evict` (designs/consolidation.md "Pods that Prevent
Consolidation"; website concepts). Owner references matter for consolidation
eligibility and daemonset exclusion.

TPU-first note: pods are deduplicated into scheduling GROUPS (identical
requests + constraints) before hitting the device — the kernel scans groups,
not pods, which turns a 10k-pod solve into an O(#deployments) scan.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

from ..apis import wellknown as wk
from ..utils.quantity import cpu_millis, mem_bytes, count as count_qty
from .requirements import Requirement, Requirements, OP_IN

ANNOTATION_DO_NOT_EVICT = "karpenter.sh/do-not-evict"
ANNOTATION_POD_DELETION_COST = "controller.kubernetes.io/pod-deletion-cost"

# group-key interning (see PodSpec.group_token). Tokens come from a monotonic
# counter and are never reused; clearing the table (pathological spec churn)
# bumps an EPOCH, and stamped tokens from older epochs are re-interned on next
# read. Invariant: at any instant, token equality <=> group-key equality
# across all live specs — so group_pods stays a pure function of the pod list
# (the solver wire protocol depends on client and server deriving identical
# group partitions from identical pods).
import itertools as _itertools
import threading as _threading

_group_key_tokens: "dict[object, int]" = {}
_group_key_counter = _itertools.count()
_group_key_epoch = 0
_group_key_lock = _threading.Lock()
_GROUP_KEY_TABLE_MAX = 1 << 20


def _intern_group_key(key) -> "tuple[int, int]":
    global _group_key_epoch
    with _group_key_lock:
        t = _group_key_tokens.get(key)
        if t is None:
            if len(_group_key_tokens) >= _GROUP_KEY_TABLE_MAX:
                _group_key_tokens.clear()
                _group_key_epoch += 1
            t = _group_key_tokens[key] = next(_group_key_counter)
        return t, _group_key_epoch


@dataclasses.dataclass(frozen=True)
class Toleration:
    key: str = ""
    operator: str = "Equal"  # Equal | Exists
    value: str = ""
    effect: str = ""  # "" tolerates all effects

    def tolerates(self, taint: "Taint") -> bool:
        if self.effect and self.effect != taint.effect:
            return False
        if not self.key:
            return self.operator == "Exists"
        if self.key != taint.key:
            return False
        if self.operator == "Exists":
            return True
        return self.value == taint.value


@dataclasses.dataclass(frozen=True)
class Taint:
    key: str
    value: str = ""
    effect: str = "NoSchedule"  # NoSchedule | PreferNoSchedule | NoExecute


def tolerates_all(tolerations: "tuple[Toleration, ...]", taints: "tuple[Taint, ...]") -> bool:
    """Pod schedulable w.r.t. taints: every NoSchedule/NoExecute taint tolerated."""
    for t in taints:
        if t.effect == "PreferNoSchedule":
            continue
        if not any(tol.tolerates(t) for tol in tolerations):
            return False
    return True


@dataclasses.dataclass(frozen=True)
class PodAffinityTerm:
    """One required pod-(anti-)affinity term: a label selector over PODS plus
    the topology key whose domains are constrained.

    Reference analogue: core scheduling's inter-pod affinity handling
    (exercised by test/suites/integration/scheduling_test.go). Selector is
    matchLabels-conjunctive (matchExpressions with op In are folded into the
    same form by the manifest loader); the scheduler resolves terms against
    resident and co-pending pods in a host pre-pass
    (oracle/scheduler.py resolve_pod_affinity)."""

    match_labels: "tuple[tuple[str, str], ...]" = ()
    topology_key: str = wk.LABEL_HOSTNAME

    def matches(self, labels: "tuple[tuple[str, str], ...]") -> bool:
        d = dict(labels)
        return all(d.get(k) == v for k, v in self.match_labels)


@dataclasses.dataclass(frozen=True)
class TopologySpreadConstraint:
    max_skew: int
    topology_key: str
    when_unsatisfiable: str = "DoNotSchedule"  # or ScheduleAnyway
    # label selector is approximated as "pods of my own group" (self-selecting
    # deployments are the overwhelmingly common case; reference E2E
    # spread-zone.yaml/spread-hostname.yaml do exactly this).


@dataclasses.dataclass(frozen=True)
class PodSpec:
    name: str
    namespace: str = "default"
    labels: "tuple[tuple[str, str], ...]" = ()  # pod labels (PDB/service selectors)
    requests: "tuple[tuple[str, int], ...]" = ()  # canonical units (cpu millis, mem bytes, counts)
    requirements: Requirements = dataclasses.field(default_factory=Requirements)
    # soft preferences (preferredDuringScheduling): an ORDERED tuple of
    # requirement terms, highest weight first. The scheduler relaxes them
    # iteratively — it tries all terms, then drops the lowest-weight term,
    # and so on down to none — taking the largest satisfiable prefix
    # (the reference core's progressive preference relaxation,
    # pkg/controllers/provisioning/scheduling preferences; exercised by
    # examples/workloads/prefer-arm.yaml). Existing-node placement ignores
    # them (first-fit order is not rescored).
    preferences: "tuple[Requirements, ...]" = ()
    tolerations: "tuple[Toleration, ...]" = ()
    topology: "tuple[TopologySpreadConstraint, ...]" = ()
    anti_affinity_hostname: bool = False  # self anti-affinity on kubernetes.io/hostname
    anti_affinity_zone: bool = False
    # required pod-(anti-)affinity with label selectors (self-selecting
    # anti-affinity uses the booleans above; these carry cross-group terms)
    pod_affinity: "tuple[PodAffinityTerm, ...]" = ()
    pod_anti_affinity: "tuple[PodAffinityTerm, ...]" = ()
    priority: int = 0
    deletion_cost: int = 0
    owner_kind: str = "ReplicaSet"  # "" => bare pod; "DaemonSet" excluded from provisioning
    do_not_evict: bool = False
    node_name: str = ""  # bound node (for cluster-state pods)
    # set by the zone-split pre-pass: the PRE-SPLIT group key, so resident
    # pods (stored with their original spec) are still counted against the
    # split subgroup's per-node caps. NOT part of group_key (it's provenance,
    # not scheduling identity).
    spread_origin: "object" = None

    def origin_key(self):
        """Identity for counting RESIDENT pods of this logical group: the
        pre-split key when this spec is a zone-split subgroup."""
        return self.spread_origin if self.spread_origin is not None \
            else self.group_key()

    def resource_vector(self) -> "list[int]":
        return wk.resource_vector(dict(self.requests))

    def is_daemon(self) -> bool:
        return self.owner_kind == "DaemonSet"

    def group_key(self):
        """Pods with equal group keys are interchangeable for scheduling.
        Memoized per instance (frozen dataclass) — grouping a 10k-pod batch
        is on the host-side critical path of every scheduling cycle."""
        k = self.__dict__.get("_group_key")
        if k is not None:
            return k
        k = (
            self.requests,
            self.requirements.canonical(),  # freezes: later in-place mutation raises
            tuple(t.canonical() for t in self.preferences),
            self.tolerations,
            self.topology,
            self.anti_affinity_hostname,
            self.anti_affinity_zone,
            self.pod_affinity,
            self.pod_anti_affinity,
            # labels separate otherwise-identical deployments: topology spread
            # is approximated as "pods of my own group", so merging across
            # selectors would balance the union instead of each deployment
            self.labels,
        )
        object.__setattr__(self, "_group_key", k)
        return k

    def group_token(self) -> int:
        """Small interned token equivalent to group_key() for dict/set use.

        The group-key tuple nests requirements/tolerations/topology and
        Python re-hashes it on EVERY dict operation — at 50k pods that
        hashing alone dominates host encode (bench config 4). The token is
        interned once per distinct key and memoized per instance, so
        steady-state grouping costs one attribute read + int-dict op per
        pod. Token equality is equivalent to key equality — stamps from a
        cleared table epoch are re-interned (see _intern_group_key)."""
        cached = self.__dict__.get("_group_token")
        if cached is not None and cached[1] == _group_key_epoch:
            return cached[0]
        t, epoch = _intern_group_key(self.group_key())
        object.__setattr__(self, "_group_token", (t, epoch))
        return t


def make_pod(
    name: str,
    cpu: "str | int" = "0",
    memory: "str | int" = "0",
    pods: int = 1,
    node_selector: "Optional[dict[str, str]]" = None,
    requirements: "Optional[Requirements]" = None,
    extended: "Optional[dict[str, int]]" = None,
    **kwargs,
) -> PodSpec:
    """Convenience constructor used by tests/fixtures (reference analogue:
    coretest pod factories, pkg/test/)."""
    reqs: dict[str, int] = {}
    if cpu:
        reqs[wk.RESOURCE_CPU] = cpu_millis(cpu)
    if memory:
        reqs[wk.RESOURCE_MEMORY] = mem_bytes(memory)
    reqs[wk.RESOURCE_PODS] = pods
    for k, v in (extended or {}).items():
        reqs[k] = count_qty(v)
    r = Requirements()
    if node_selector:
        r = r.union(Requirements.from_node_selector(node_selector))
    if requirements:
        r = r.union(requirements)
    return PodSpec(
        name=name,
        requests=tuple(sorted(reqs.items())),
        requirements=r,
        **kwargs,
    )


@dataclasses.dataclass
class PodGroup:
    """A deduplicated batch of identical pods."""

    spec: PodSpec
    count: int
    pod_names: "list[str]"

    @property
    def vector(self) -> "list[int]":
        return self.spec.resource_vector()


def group_pods(pods: "list[PodSpec]") -> "list[PodGroup]":
    # int-token keys, not the key tuples: re-hashing the nested tuples per
    # lookup dominated 50k-pod host encode (see PodSpec.group_token).
    # Token equality == key equality only WITHIN one table epoch: if the
    # intern table clears mid-pass (2^20 distinct keys, or a concurrent
    # thread's clear), a token already used as a dict key here could split
    # from an equal-key pod interned after the clear. Snapshot the epoch
    # around the pass and redo it on the (rare) mismatch so the result is
    # always a single-epoch partition — a pure function of the pod list.
    # Bounded retries: under epoch churn faster than a pass (many threads
    # interning disjoint key floods), fall back to grouping by the raw key
    # tuples — slower, but correct without any epoch assumption.
    for _ in range(3):
        epoch_before = _group_key_epoch
        # accumulate only the name lists (count == len) and a representative
        # pod per token; building PodGroups inside the loop costs two extra
        # attribute ops per pod, and with the warm-path token read inlined
        # (a bound-method call per pod is ~1ms at 10k pods) this loop is the
        # per-cycle host-encode floor
        names: "dict[int, list[str]]" = {}
        first: "dict[int, PodSpec]" = {}
        get = names.get
        for p in pods:
            c = p.__dict__.get("_group_token")
            tok = c[0] if (c is not None and c[1] == epoch_before) \
                else p.group_token()
            lst = get(tok)
            if lst is None:
                names[tok] = [p.name]
                first[tok] = p
            else:
                lst.append(p.name)
        if _group_key_epoch == epoch_before:
            return [PodGroup(spec=first[t], count=len(ns), pod_names=ns)
                    for t, ns in names.items()]
    bykey: "dict[object, PodGroup]" = {}
    for p in pods:
        g = bykey.get(p.group_key())
        if g is None:
            bykey[p.group_key()] = PodGroup(spec=p, count=1, pod_names=[p.name])
        else:
            g.count += 1
            g.pod_names.append(p.name)
    return list(bykey.values())
