"""Scheduling requirements algebra.

Parity target: karpenter-core's `scheduling.Requirements` /
`NewRequirement(key, op, values...)` / `.Compatible()` / `.Intersects()` — the
constraint algebra the reference consumes at
/root/reference/pkg/cloudprovider/instancetype.go:67-117 (instance-type
requirements construction), cloudprovider.go:315-321 (compatibility filter) and
amifamily/ami.go:112-119 (AMI requirement matching).

A requirement is a constraint on one label key with an operator:
In / NotIn / Exists / DoesNotExist / Gt / Lt. A `Requirements` object is a
per-key conjunction. Sets with the NotIn operator are modeled as complement
("everything except values"), like the reference's complement sets; Gt/Lt keep
integer bounds alongside.

This host-side algebra is the exact-semantics spec. The TPU path folds each
Requirements object into a dense boolean mask over the instance-type axis (see
karpenter_tpu/ops/masks.py) — the fold is checked against this module
property-test-style in tests/test_masks.py.
"""

from __future__ import annotations

import dataclasses
from typing import Iterable, Optional

OP_IN = "In"
OP_NOT_IN = "NotIn"
OP_EXISTS = "Exists"
OP_DOES_NOT_EXIST = "DoesNotExist"
OP_GT = "Gt"
OP_LT = "Lt"
OPERATORS = (OP_IN, OP_NOT_IN, OP_EXISTS, OP_DOES_NOT_EXIST, OP_GT, OP_LT)


class IncompatibleError(ValueError):
    """Raised when two Requirements cannot be satisfied simultaneously."""


@dataclasses.dataclass(frozen=True)
class Requirement:
    """A single (key, operator, values) constraint, normalized to set form.

    complement=False: allowed iff value in `values` (plus bounds).
    complement=True:  allowed iff value not in `values` (plus bounds).
    forbid_key=True:  the key must be ABSENT (DoesNotExist).
    """

    key: str
    complement: bool = False
    values: frozenset = frozenset()
    gt: Optional[int] = None  # exclusive lower bound
    lt: Optional[int] = None  # exclusive upper bound
    forbid_key: bool = False
    # True when the key MUST be present (In/Exists/Gt/Lt); survives
    # intersection so Exists ∩ NotIn still requires presence.
    requires_presence: bool = True

    @staticmethod
    def create(key: str, op: str, values: Iterable[str] = ()) -> "Requirement":
        values = tuple(str(v) for v in values)
        if op == OP_IN:
            return Requirement(key, complement=False, values=frozenset(values))
        if op == OP_NOT_IN:
            return Requirement(key, complement=True, values=frozenset(values),
                               requires_presence=False)
        if op == OP_EXISTS:
            return Requirement(key, complement=True, values=frozenset())
        if op == OP_DOES_NOT_EXIST:
            return Requirement(key, forbid_key=True, requires_presence=False)
        if op == OP_GT:
            (v,) = values
            return Requirement(key, complement=True, values=frozenset(), gt=int(v))
        if op == OP_LT:
            (v,) = values
            return Requirement(key, complement=True, values=frozenset(), lt=int(v))
        raise ValueError(f"unknown operator {op!r}")

    # -- value membership ---------------------------------------------------------

    def has(self, value: str) -> bool:
        """Does a concrete label value satisfy this requirement?"""
        if self.forbid_key:
            return False
        if self.complement:
            if value in self.values:
                return False
        else:
            if value not in self.values:
                return False
        if self.gt is not None or self.lt is not None:
            try:
                num = int(value)
            except ValueError:
                return False
            if self.gt is not None and not num > self.gt:
                return False
            if self.lt is not None and not num < self.lt:
                return False
        return True

    def allows_absent(self) -> bool:
        """Is an object WITHOUT this key acceptable?

        k8s nodeSelectorTerm semantics: In/Exists/Gt/Lt fail on a missing
        label; NotIn and DoesNotExist succeed. The requires_presence bit makes
        this survive intersections (Exists ∩ NotIn still requires presence).
        """
        if self.forbid_key:
            return True
        return not self.requires_presence

    # -- set algebra --------------------------------------------------------------

    def intersect(self, other: "Requirement") -> "Requirement":
        assert self.key == other.key
        if self.forbid_key or other.forbid_key:
            # DoesNotExist ∩ anything-presence-requiring = empty; with
            # absence-tolerant sets, result is still "key must be absent".
            if (self.forbid_key or self.allows_absent()) and (
                other.forbid_key or other.allows_absent()
            ):
                return Requirement(self.key, forbid_key=True, requires_presence=False)
            raise IncompatibleError(f"key {self.key}: DoesNotExist vs presence-requiring")
        gt = self.gt if other.gt is None else (other.gt if self.gt is None else max(self.gt, other.gt))
        lt = self.lt if other.lt is None else (other.lt if self.lt is None else min(self.lt, other.lt))
        if not self.complement and not other.complement:
            values = self.values & other.values
            complement = False
        elif self.complement and other.complement:
            values = self.values | other.values
            complement = True
        else:
            allow = self.values if not self.complement else other.values
            deny = other.values if not self.complement else self.values
            values = allow - deny
            complement = False
        req = Requirement(self.key, complement=complement, values=values, gt=gt, lt=lt,
                          requires_presence=self.requires_presence or other.requires_presence)
        if req.definitely_empty():
            raise IncompatibleError(f"key {self.key}: empty intersection")
        return req

    def definitely_empty(self) -> bool:
        if self.forbid_key:
            return False
        if not self.complement:
            return not any(self.has(v) for v in self.values)
        if self.gt is not None and self.lt is not None and self.lt - self.gt <= 1:
            return True
        return False

    def intersects(self, other: "Requirement") -> bool:
        try:
            self.intersect(other)
            return True
        except IncompatibleError:
            return False


class Requirements:
    """Per-key conjunction of Requirements, with karpenter-core's algebra."""

    def __init__(self, reqs: Iterable[Requirement] = ()):
        self._by_key: dict[str, Requirement] = {}
        self._specs_cache: "Optional[list]" = None
        self._frozen = False
        for r in reqs:
            self.add(r)

    @staticmethod
    def of(*specs: "tuple[str, str, Iterable[str]] | tuple[str, str]") -> "Requirements":
        out = Requirements()
        for spec in specs:
            key, op, *rest = spec
            out.add(Requirement.create(key, op, rest[0] if rest else ()))
        return out

    @staticmethod
    def from_node_selector(selector: "dict[str, str]") -> "Requirements":
        return Requirements(
            Requirement.create(k, OP_IN, [v]) for k, v in sorted(selector.items())
        )

    @staticmethod
    def from_labels(labels: "dict[str, str]") -> "Requirements":
        """Instance-type labels -> single-valued In requirements.

        Reference analogue: computeRequirements at instancetype.go:67-117.
        """
        return Requirements(
            Requirement.create(k, OP_IN, [v]) for k, v in sorted(labels.items())
        )

    def add(self, req: Requirement) -> None:
        if self._frozen:
            raise RuntimeError(
                "Requirements mutated after being hashed/canonicalized; "
                "mutate a .copy() instead (copy-on-write contract)")
        existing = self._by_key.get(req.key)
        self._by_key[req.key] = existing.intersect(req) if existing else req
        self._specs_cache = None

    def keys(self):
        return self._by_key.keys()

    def get(self, key: str) -> Optional[Requirement]:
        return self._by_key.get(key)

    def __iter__(self):
        return iter(self._by_key.values())

    def __len__(self):
        return len(self._by_key)

    def canonical(self) -> "tuple[tuple[str, str, tuple[str, ...]], ...]":
        """THE canonical hashable form (single owner — group_key dedupe,
        __eq__/__hash__, and wire round-trip identity all route through
        here). Freezes the object: publication into a hash/memo key makes
        later in-place mutation a bug, so add() refuses it afterwards."""
        self._frozen = True
        return tuple((k, op, tuple(v)) for k, op, v in self.to_specs())

    def __eq__(self, other) -> bool:
        """Canonical (spec-level) equality: two Requirements are equal iff
        they emit identical to_specs(), the same canonical form group_key
        dedupe relies on — so wire round trips compare equal."""
        if not isinstance(other, Requirements):
            return NotImplemented
        return self.to_specs() == other.to_specs()

    def __hash__(self) -> int:
        return hash(self.canonical())

    def copy(self) -> "Requirements":
        out = Requirements()
        out._by_key = dict(self._by_key)
        out._specs_cache = self._specs_cache
        return out

    def union(self, other: "Requirements") -> "Requirements":
        """Conjunction of both (karpenter-core's Requirements.Add/Intersect)."""
        out = self.copy()
        for r in other:
            out.add(r)
        return out

    def matches_labels(self, labels: "dict[str, str]") -> bool:
        """Do concrete labels (e.g. an instance type's) satisfy every requirement?"""
        for key, req in self._by_key.items():
            if key in labels:
                if not req.has(labels[key]):
                    return False
            else:
                if not req.allows_absent():
                    return False
        return True

    def compatible(self, other: "Requirements") -> bool:
        """Non-empty intersection per key (karpenter-core Requirements.Compatible,
        consumed at cloudprovider.go:315-321)."""
        for key in set(self._by_key) | set(other._by_key):
            a, b = self._by_key.get(key), other._by_key.get(key)
            if a is None or b is None:
                req = a or b
                # A lone In/Exists/Gt/Lt is satisfiable by SOME labeled object;
                # compatibility against the wildcard side always holds.
                if req.definitely_empty():
                    return False
                continue
            if not a.intersects(b):
                return False
        return True

    def to_specs(self) -> "list[tuple[str, str, list[str]]]":
        """Serialize to (key, op, values) triples (wire/CRD form).

        Canonical: semantically-equal Requirements produce identical specs (a
        key may emit several triples — e.g. a merged Gt+Lt emits both). Used
        by PodSpec.group_key(), so canonicality is load-bearing for dedupe.
        Memoized (hot in 10k-pod group dedupe).
        """
        if self._specs_cache is not None:
            return self._specs_cache
        out = []
        for key, r in sorted(self._by_key.items()):
            if r.forbid_key:
                out.append((key, OP_DOES_NOT_EXIST, []))
            elif not r.complement:
                # bounds folded into the explicit value set
                out.append((key, OP_IN, sorted(v for v in r.values if r.has(v))))
            else:
                implies_presence = False
                if r.values:
                    out.append((key, OP_NOT_IN, sorted(r.values)))
                if r.gt is not None:
                    out.append((key, OP_GT, [str(r.gt)]))
                    implies_presence = True
                if r.lt is not None:
                    out.append((key, OP_LT, [str(r.lt)]))
                    implies_presence = True
                # NotIn alone doesn't imply presence; emit Exists when the
                # requirement demands it (e.g. merged Exists ∩ NotIn)
                if r.requires_presence and not implies_presence:
                    out.append((key, OP_EXISTS, []))
        self._specs_cache = out
        return out

    def __repr__(self):
        return f"Requirements({self.to_specs()!r})"
