"""Host-side encoding: scheduling problem -> dense device arrays.

This is the TPU-first redesign of the reference's per-object hot loop
(/root/reference/designs/bin-packing.md:28-43 + pkg/cloudprovider/
cloudprovider.go:302-321 resolveInstanceTypes): every label/taint constraint is
folded ON HOST into boolean feasibility masks over a static (instanceType x
zone x capacityType) option grid, so the device kernel sees only dense int32
capacity math. Pods are deduplicated into groups (identical spec => identical
mask), so mask folding cost is O(#deployments), not O(#pods).

The folding reuses the oracle's exact matching code (feasible_options), which
guarantees the kernel and the scalar fallback agree on WHICH options are
feasible by construction; the kernel is differential-tested on the packing
arithmetic only.

Catalog-side arrays are versioned by Catalog.seqnum (the reference's
instance-type cache seqnum trick, instancetypes.go:62-68) so they can stay
device-resident across solves; only the group arrays ship per solve.
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Sequence

import numpy as np

from ..apis import wellknown as wk
from ..apis.provisioner import Provisioner
from ..models.instancetype import Catalog
from ..models.pod import PodGroup, PodSpec
from ..models.requirements import IncompatibleError, Requirements
from ..models.pod import tolerates_all
from .cluster import ExistingColumns
from ..oracle.scheduler import (
    ExistingNode, Option, feasible_options, prepare_groups, _group_cap_per_node,
    kubelet_is_default, kubelet_overhead_vector, kubelet_pods_cap,
)

INT_BIG = np.int32(2**30)


@dataclasses.dataclass
class KeyCol:
    codes: np.ndarray             # i32 [T*S]; -1 = absent (value interned per key)
    vocab: "dict[str, int]"       # value -> code
    num: np.ndarray               # float64 [T*S]; nan = absent/non-numeric


@dataclasses.dataclass
class GridCols:
    """Per-key integer-coded label columns over the flat option axis, for
    vectorized requirement folding (the numpy fast path of feasible_options;
    checked equal to the scalar path in tests/test_encode.py)."""

    cols: "dict[str, KeyCol]"
    flat_valid: np.ndarray  # bool [T*S]


def build_cols(grid: "OptionGrid") -> GridCols:
    n = len(grid.options)
    raw: "dict[str, list]" = {}
    flat_valid = np.zeros(n, dtype=bool)
    labels_per_opt: "list[Optional[dict]]" = []
    for i, o in enumerate(grid.options):
        if o is None:
            labels_per_opt.append(None)
            continue
        flat_valid[i] = True
        d = dict(o.itype.labels)
        d[wk.LABEL_ZONE] = o.zone
        d[wk.LABEL_CAPACITY_TYPE] = o.capacity_type
        labels_per_opt.append(d)
        for k in d:
            raw.setdefault(k, None)
    cols: "dict[str, KeyCol]" = {}
    for k in raw:
        codes = np.full(n, -1, dtype=np.int32)
        num = np.full(n, np.nan)
        vocab: "dict[str, int]" = {}
        for i, d in enumerate(labels_per_opt):
            if d is None or k not in d:
                continue
            v = d[k]
            code = vocab.get(v)
            if code is None:
                code = vocab[v] = len(vocab)
            codes[i] = code
            try:
                num[i] = int(v)
            except ValueError:
                pass
        cols[k] = KeyCol(codes, vocab, num)
    return GridCols(cols, flat_valid)


def fold_option_mask(reqs: Requirements, cols: GridCols, prov: Provisioner) -> np.ndarray:
    """Requirements -> bool mask over flat options, under provisioner `prov`'s
    label overlay. Vectorized equivalent of
    `reqs.matches_labels(option_labels(opt, prov))` per option."""
    n = cols.flat_valid.shape[0]
    mask = cols.flat_valid.copy()
    overlay = {wk.LABEL_PROVISIONER: prov.name}
    for k, v in prov.labels:
        overlay.setdefault(k, v)
    for req in reqs:
        kc = cols.cols.get(req.key)
        if kc is None:
            # key not on any option: provisioner overlay or absent everywhere
            value = overlay.get(req.key)
            ok = req.has(value) if value is not None else req.allows_absent()
            if not ok:
                return np.zeros(n, dtype=bool)
            continue
        codes, num = kc.codes, kc.num
        present = codes >= 0
        fill_value = overlay.get(req.key)
        if fill_value is not None:
            # provisioner label fills options that lack the key
            # (option_labels setdefault semantics): absent slots behave as
            # carrying fill_value, membership + bounds included.
            absent_ok = req.has(fill_value)
        else:
            absent_ok = req.allows_absent()
        if req.forbid_key:
            ok = np.where(present, False, absent_ok)
        else:
            value_codes = [kc.vocab[v] for v in req.values if v in kc.vocab]
            hits = np.isin(codes, value_codes) if value_codes else np.zeros(n, bool)
            ok_present = ~hits if req.complement else hits
            if req.gt is not None or req.lt is not None:
                with np.errstate(invalid="ignore"):
                    if req.gt is not None:
                        ok_present &= num > req.gt
                    if req.lt is not None:
                        ok_present &= num < req.lt
            ok = np.where(present, ok_present, absent_ok)
        mask &= ok
    return mask


@dataclasses.dataclass
class OptionGrid:
    """Static (T x S) option lattice; S enumerates (zone, capacityType) pairs.

    Flat option index = t * S + s, giving a stable bijection with the
    oracle's Option list built from the same iteration order.
    """

    catalog: Catalog
    zones: "list[str]"
    capacity_types: "list[str]"
    options: "list[Optional[Option]]"  # length T*S, None where no offering DEFINED
    valid: np.ndarray  # bool [T, S] — offering defined AND currently available
    price: np.ndarray  # f32 [T, S]
    tiebreak: np.ndarray  # i32 [T, S], rank in (price, spot-first, name, zone) order
    alloc_t: np.ndarray  # i32 [T, R]
    seqnum: int
    cols: "Optional[GridCols]" = None  # lazily built label columns
    layout_key: int = 0  # availability-independent content fingerprint

    def get_cols(self) -> "GridCols":
        if self.cols is None:
            self.cols = build_cols(self)
        return self.cols

    def active_zones(self) -> "list[str]":
        """Zones with at least one AVAILABLE option — the zone-spread
        universe, which must match the oracle's (it builds options from
        available offerings only; build_options, oracle/scheduler.py)."""
        C = len(self.capacity_types)
        v = self.valid.reshape(self.T, len(self.zones), C)
        act = v.any(axis=(0, 2))
        return [z for zi, z in enumerate(self.zones) if act[zi]]

    @property
    def T(self):
        return len(self.catalog.types)

    @property
    def S(self):
        return len(self.zones) * len(self.capacity_types)

    def flat_options(self) -> "list[Option]":
        return [o for o in self.options if o is not None]


def grid_layout_key(catalog: Catalog) -> int:
    """Fingerprint of everything a grid depends on EXCEPT offering
    availability: type names/labels/allocatables and the defined offering
    lattice with prices. ICE marks (and expiries) flip only availability,
    so two catalogs with equal layout keys share every static grid array —
    the spot-storm fast path (an ICE seqnum bump then costs a [T,S] mask
    refresh instead of a full grid + group-encode rebuild)."""
    return hash(tuple(
        (t.name, tuple(sorted(t.labels_dict().items())),
         tuple(int(a) for a in t.allocatable_vector()),
         tuple(sorted((o.zone, o.capacity_type, float(o.price))
                      for o in t.offerings)))
        for t in catalog.types))


def build_grid(catalog: Catalog,
               reuse: "Optional[OptionGrid]" = None) -> OptionGrid:
    """Build the option lattice over every DEFINED offering; `valid` carries
    current availability separately. The zone-spread universe the oracle
    must agree on comes from active_zones() (available only), not from the
    static `zones` axis. When `reuse` has the same layout_key, its static
    arrays (options, price, tiebreak, alloc_t, label cols) are shared and
    only `valid` is recomputed."""
    key = grid_layout_key(catalog)
    if reuse is not None and reuse.layout_key == key:
        S = reuse.S
        valid = np.zeros_like(reuse.valid)
        zi_of = {z: i for i, z in enumerate(reuse.zones)}
        ci_of = {c: i for i, c in enumerate(reuse.capacity_types)}
        for ti, t in enumerate(catalog.types):
            for o in t.offerings:
                if o.available:
                    si = zi_of[o.zone] * len(reuse.capacity_types) \
                        + ci_of[o.capacity_type]
                    valid[ti, si] = True
        return OptionGrid(catalog, reuse.zones, reuse.capacity_types,
                          reuse.options, valid, reuse.price, reuse.tiebreak,
                          reuse.alloc_t, catalog.seqnum, cols=reuse.cols,
                          layout_key=key)
    zones = sorted({o.zone for t in catalog.types for o in t.offerings})
    cts = list(wk.CAPACITY_TYPES)  # on-demand, spot
    T, S = len(catalog.types), len(zones) * len(cts)
    options: "list[Optional[Option]]" = [None] * (T * S)
    valid = np.zeros((T, S), dtype=bool)
    price = np.full((T, S), np.inf, dtype=np.float32)
    alloc_t = np.zeros((T, wk.NUM_RESOURCES), dtype=np.int32)
    for ti, t in enumerate(catalog.types):
        alloc_t[ti] = np.minimum(t.allocatable_vector(), INT_BIG)
        defined = {(o.zone, o.capacity_type): o for o in t.offerings}
        for zi, z in enumerate(zones):
            for ci, ct in enumerate(cts):
                o = defined.get((z, ct))
                if o is None:
                    continue
                si = zi * len(cts) + ci
                flat = ti * S + si
                options[flat] = Option(flat, t, z, ct, o.price, tuple(int(a) for a in alloc_t[ti]))
                valid[ti, si] = o.available
                price[ti, si] = o.price
    # tiebreak rank: identical key to Option.sort_key (oracle decision
    # order). Ranking over the DEFINED universe preserves the relative
    # order of the available subset the oracle ranks; the kernel compares
    # ranks only within availability-masked feasible sets.
    tiebreak = np.full((T, S), INT_BIG, dtype=np.int32)
    ranked = sorted((o for o in options if o is not None), key=Option.sort_key)
    for rank, o in enumerate(ranked):
        tiebreak[o.index // S, o.index % S] = rank
    return OptionGrid(catalog, zones, cts, options, valid, price, tiebreak,
                      alloc_t, catalog.seqnum, layout_key=key)


def kubelet_arrays(
    provs: "list[Provisioner]", catalog: Catalog
) -> "tuple[Optional[np.ndarray], Optional[np.ndarray]]":
    """(prov_overhead [Pv, R], prov_pods_cap [Pv, T]) — None, None when every
    provisioner runs kubelet defaults (keeps the compiled kernel unchanged
    for the common case; the reference hashes kubelet config into its
    instance-type cache key the same way, instancetypes.go:104-111)."""
    if all(kubelet_is_default(p.kubelet) for p in provs):
        return None, None
    Pv, T, R = len(provs), len(catalog.types), wk.NUM_RESOURCES
    ovh = np.zeros((max(Pv, 1), R), dtype=np.int32)
    cap = np.full((max(Pv, 1), max(T, 1)), INT_BIG, dtype=np.int32)
    cores = [max(1, dict(t.capacity).get(wk.RESOURCE_CPU, 1000) // 1000)
             for t in catalog.types]
    for pi, p in enumerate(provs):
        ovh[pi] = np.minimum(kubelet_overhead_vector(p.kubelet), INT_BIG)
        for ti, t in enumerate(catalog.types):
            c = kubelet_pods_cap(p.kubelet, t, cores=cores[ti])
            if c is not None:
                cap[pi, ti] = min(c, int(INT_BIG))
    return ovh, cap


@dataclasses.dataclass
class EncodedProblem:
    """Everything the packer kernel consumes, as numpy (device-put by caller)."""

    # catalog side (device-resident across solves, keyed by grid.seqnum)
    alloc_t: np.ndarray    # i32 [T, R]
    valid: np.ndarray      # bool [T, S]
    tiebreak: np.ndarray   # i32 [T, S]
    # per-solve group side
    group_vec: np.ndarray     # i32 [G, R]
    group_count: np.ndarray   # i32 [G]
    group_cap: np.ndarray     # i32 [G]  (INT_BIG when uncapped)
    group_feas: np.ndarray    # bool [G, Pv, T, S]
    group_newprov: np.ndarray  # i32 [G]  (-1: no provisioner admits)
    overhead: np.ndarray      # i32 [R] daemonset overhead on fresh nodes
    # existing nodes
    ex_alloc: np.ndarray   # i32 [Ne, R]
    ex_used: np.ndarray    # i32 [Ne, R]
    ex_feas: np.ndarray    # bool [G, Ne]
    n_slots: int           # N: max new node claims (static)
    # bookkeeping for decode
    groups: "list[PodGroup]"
    provisioners: "list[Provisioner]"
    grid: OptionGrid
    # per-provisioner kubelet effects (None when all defaults)
    prov_overhead: "Optional[np.ndarray]" = None  # i32 [Pv, R]
    prov_pods_cap: "Optional[np.ndarray]" = None  # i32 [Pv, T]
    # remaining per-(group, existing-node) cap; None when no group is capped
    ex_cap: "Optional[np.ndarray]" = None  # i32 [G, Ne]
    # origin-representative row per group (first row sharing origin_key):
    # zone-split subgroups of one deployment share one per-node cap budget
    group_origin: "Optional[np.ndarray]" = None  # i32 [G]


def encode_problem(
    catalog: Catalog,
    provisioners: Sequence[Provisioner],
    pods: "list[PodSpec]",
    existing: Sequence[ExistingNode] = (),
    daemon_overhead: Optional[Sequence[int]] = None,
    n_slots: Optional[int] = None,
    grid: Optional[OptionGrid] = None,
    group_cache: "Optional[dict]" = None,
    option_mask: Optional[np.ndarray] = None,
) -> EncodedProblem:
    """`group_cache` (owned by a solver instance whose provisioner set is
    fixed) memoizes encode_group results across solves keyed by (group key,
    grid seqnum, daemon overhead): steady-state controllers re-solve the
    same deployments against an unchanged grid, and the mask folding is the
    dominant per-group cost (the reference memoizes the analogous
    instance-type construction, instancetypes.go:104-120).

    `option_mask` (bool [T, S], the spot plane's diversity-floor dimension)
    ANDs into availability for NEW-node admission only — existing-node
    feasibility is untouched, matching the oracle's barred-option filter.
    The final cache level is bypassed while a mask is active (masks change
    within a solve loop); the static folds are still reused."""
    if grid is None or grid.seqnum != catalog.seqnum:
        grid = build_grid(catalog, reuse=grid)
    provs = sorted(provisioners, key=lambda p: (-p.weight, p.name))
    overhead = list(daemon_overhead or [0] * wk.NUM_RESOURCES)
    # zone-spread universe = zones with AVAILABLE options (parity with the
    # oracle's available-offering universe; the grid's static zone axis
    # spans all DEFINED offerings)
    groups = prepare_groups(pods, grid.active_zones(), existing)
    G, Pv, T, S = len(groups), len(provs), grid.T, grid.S
    R = wk.NUM_RESOURCES

    group_vec = np.zeros((max(G, 1), R), dtype=np.int32)
    group_count = np.zeros((max(G, 1),), dtype=np.int32)
    group_cap = np.full((max(G, 1),), INT_BIG, dtype=np.int32)
    group_feas = np.zeros((max(G, 1), max(Pv, 1), T, S), dtype=bool)
    group_newprov = np.full((max(G, 1),), -1, dtype=np.int32)
    ex_alloc = np.zeros((max(len(existing), 1), R), dtype=np.int32)
    ex_used = np.zeros((max(len(existing), 1), R), dtype=np.int32)
    ex_feas = np.zeros((max(G, 1), max(len(existing), 1)), dtype=bool)

    # HOT:BEGIN(existing-encode) — per-node work here must be vectorized;
    # hack/check_hot_loops.py bans new per-pod/per-node Python loops
    ex_cols = existing if isinstance(existing, ExistingColumns) else None
    if ex_cols is not None:
        ne = len(ex_cols)
        ex_alloc[:ne] = np.minimum(ex_cols.alloc_rows, INT_BIG)
        ex_used[:ne] = np.minimum(ex_cols.used_rows, INT_BIG)
    else:
        # hot-loop-ok: legacy dataclass-view compatibility path (round-2
        # carry lists, oracle callers); the columnar branch above is the
        # production path
        for ei, e in enumerate(existing):
            ex_alloc[ei] = np.minimum(e.allocatable, INT_BIG)
            ex_used[ei] = np.minimum(e.used, INT_BIG)

    prov_overhead, prov_pods_cap = kubelet_arrays(provs, catalog)

    # Subgroups sharing an origin (ScheduleAnyway zone splits differ only in
    # soft preferences) consume ONE per-node cap budget — the kernel's carried
    # ex_placed/claim_placed counters are keyed by this representative row,
    # mirroring the oracle's origin-keyed group_counts.
    group_origin = np.arange(max(G, 1), dtype=np.int32)
    first_by_origin: "dict[object, int]" = {}
    for gi, g in enumerate(groups):
        group_origin[gi] = first_by_origin.setdefault(g.spec.origin_key(), gi)

    cols = grid.get_cols()
    if group_cache is not None:
        # two-level invalidation: the STATIC level (requirement folds over
        # the defined universe) survives ICE seqnum churn and clears only
        # on layout changes; the FINAL level (availability folded in) is
        # per-seqnum. A spot storm then costs cheap mask ANDs per group,
        # not a re-fold (the reference's analogous split is the seqnum-
        # keyed ICE cache atop the long-lived instance-type cache,
        # instancetypes.go:104-120 + unavailableofferings.go:31-80).
        if group_cache.get("layout") != grid.layout_key:
            group_cache.clear()
            group_cache["layout"] = grid.layout_key
            group_cache["static"] = {}
        if group_cache.get("seqnum") != grid.seqnum:
            group_cache["seqnum"] = grid.seqnum
            group_cache["entries"] = {}
    ovh_key = tuple(overhead)
    avail = grid.valid if option_mask is None else (grid.valid & option_mask)
    for gi, g in enumerate(groups):
        entry = None
        ck = None
        if group_cache is not None:
            ck = (g.spec.group_key(), ovh_key)
            if option_mask is None:
                entry = group_cache["entries"].get(ck)
        if entry is None:
            static = group_cache["static"].get(ck) if ck is not None else None
            if static is None:
                static = encode_group_static(
                    g, provs, grid, cols, overhead,
                    prov_overhead=prov_overhead, prov_pods_cap=prov_pods_cap)
                if ck is not None:
                    statics = group_cache["static"]
                    if len(statics) > 2048:  # bound churny-workload growth
                        statics.clear()
                    statics[ck] = static
            entry = combine_group(static, avail)
            if ck is not None and option_mask is None:
                entries = group_cache["entries"]
                if len(entries) > 2048:
                    entries.clear()
                entries[ck] = entry
        vec, cap, feas, newprov = entry
        group_vec[gi] = vec
        group_count[gi] = g.count
        group_cap[gi] = cap
        group_feas[gi] = feas
        group_newprov[gi] = newprov
        if ex_cols is not None:
            ex_feas[gi, :len(ex_cols)] = existing_fit_vector(ex_cols, g.spec)
        else:
            # hot-loop-ok: legacy dataclass-view compatibility path
            for ei, e in enumerate(existing):
                ex_feas[gi, ei] = _ex_label_fit(e, g.spec)

    # Per-existing-node REMAINING group caps: hostname spread/anti-affinity
    # counts pods already RESIDENT on the node (the oracle does the same via
    # ExistingNode.group_counts seeding). When present, this array REPLACES
    # the scalar group_cap on the existing-node path, so capped groups get
    # their cap here even on resident-free nodes.
    ex_cap = None
    if existing and any(int(c) < int(INT_BIG) for c in group_cap[:max(G, 1)]):
        ex_cap = np.full((max(G, 1), max(len(existing), 1)), INT_BIG,
                         dtype=np.int32)
        for gi, g in enumerate(groups):
            cap = int(group_cap[gi])
            if cap >= int(INT_BIG):
                continue
            # residents carry their PRE-SPLIT spec: count via origin key;
            # group_counts carries IN-RUN placements from an earlier solve
            # round (the two-round co-pending affinity driver) — the oracle's
            # cap check is resident_counts[okey] + group_counts[okey]
            okey = g.spec.origin_key()
            if ex_cols is not None:
                remaining = cap - ex_cols.resident_count_vector(okey)
                # in-run placements (group_counts) only exist on views some
                # earlier consumer materialized; a fresh snapshot has none
                for ei, view in ex_cols._views.items():  # hot-loop-ok: sparse
                    remaining[ei] -= view.group_counts.get(okey, 0)
                ex_cap[gi, :len(ex_cols)] = np.maximum(0, remaining)
            else:
                # hot-loop-ok: legacy dataclass-view compatibility path
                for ei, e in enumerate(existing):
                    ex_cap[gi, ei] = max(0, cap
                                         - e.resident_counts.get(okey, 0)
                                         - e.group_counts.get(okey, 0))
    # HOT:END(existing-encode)

    if n_slots is None:
        # Tight upper bound on claim slots: group g opens at most
        # ceil(count_g / kstar_g) fresh nodes, kstar_g = max pods-per-fresh-node
        # over its admitting provisioner's feasible types (kernel step 3 math).
        bound = 0
        alloc64 = grid.alloc_t.astype(np.int64)
        ovh = np.asarray(overhead, dtype=np.int64)
        pods_i = wk.RESOURCE_INDEX[wk.RESOURCE_PODS]
        for gi, g in enumerate(groups):
            pi = int(group_newprov[gi])
            if pi < 0:
                continue
            vec = group_vec[gi].astype(np.int64)
            ovh_p = ovh if prov_overhead is None \
                else ovh + prov_overhead[pi].astype(np.int64)
            q0 = np.where(vec[None, :] > 0,
                          (alloc64 - ovh_p[None, :]) // np.maximum(vec[None, :], 1),
                          INT_BIG)
            q0 = np.where(alloc64 - ovh_p[None, :] < 0, -1, q0).min(axis=1)
            if prov_pods_cap is not None and vec[pods_i] > 0:
                q0 = np.minimum(q0, np.maximum(
                    (prov_pods_cap[pi].astype(np.int64) - ovh_p[pods_i])
                    // vec[pods_i], -1))
            feas_t = group_feas[gi, pi].any(axis=1)
            kstar = int(min(max(q0[feas_t].max(initial=0), 0), group_cap[gi]))
            if kstar > 0:
                bound += -(-int(group_count[gi]) // kstar)
        n_slots = max(8, bound)

    return EncodedProblem(
        alloc_t=grid.alloc_t, valid=grid.valid, tiebreak=grid.tiebreak,
        group_vec=group_vec, group_count=group_count, group_cap=group_cap,
        group_feas=group_feas, group_newprov=group_newprov,
        overhead=np.asarray(overhead, dtype=np.int32),
        ex_alloc=ex_alloc, ex_used=ex_used, ex_feas=ex_feas,
        n_slots=n_slots,
        groups=groups, provisioners=list(provs), grid=grid,
        prov_overhead=prov_overhead, prov_pods_cap=prov_pods_cap,
        ex_cap=ex_cap, group_origin=group_origin,
    )


@dataclasses.dataclass
class GroupStatic:
    """Availability-independent encode of one pod group: valid as long as
    the grid LAYOUT (types/labels/allocs/defined offerings) is unchanged,
    i.e. across ICE seqnum bumps. combine_group folds current availability
    in — together they are bit-identical to the one-shot encode_group."""

    vec: np.ndarray  # i32 [R]
    cap: int
    n_provs: int
    # (pi, base mask [T,S] pre-availability, pref masks in k-descending
    # relaxation order, each pre-availability)
    per_prov: "list[tuple[int, np.ndarray, list[np.ndarray]]]"


def encode_group_static(
    group: PodGroup,
    provs: "list[Provisioner]",
    grid: OptionGrid,
    cols: GridCols,
    overhead: Sequence[int],
    prov_overhead: Optional[np.ndarray] = None,
    prov_pods_cap: Optional[np.ndarray] = None,
) -> GroupStatic:
    """The fold half of the admission rule (tolerations ∧ requirements ∧
    fresh-node capacity) over the DEFINED option universe — everything
    except current offering availability."""
    T, S = grid.T, grid.S
    vec = np.minimum(group.vector, INT_BIG).astype(np.int32)
    cap = _group_cap_per_node(group.spec)
    cap = INT_BIG if cap is None else cap
    per_prov: "list[tuple[int, np.ndarray, list[np.ndarray]]]" = []
    ovh = np.asarray(overhead, dtype=np.int64)
    alloc64 = grid.alloc_t.astype(np.int64)
    vec64 = vec.astype(np.int64)
    fits_default = np.all(alloc64 - ovh[None, :] - vec64[None, :] >= 0, axis=1)
    pods_i = wk.RESOURCE_INDEX[wk.RESOURCE_PODS]
    for pi, prov in enumerate(provs):
        if not tolerates_all(group.spec.tolerations, prov.taints):
            continue
        try:
            reqs = prov.scheduling_requirements().union(group.spec.requirements)
        except IncompatibleError:
            continue
        if prov_overhead is None:
            fits_t = fits_default
        else:
            # kubelet-adjusted fresh-node fit: extra reserved overhead plus
            # the pods cap must still admit one pod (oracle feasible_options)
            ovh_p = ovh + prov_overhead[pi].astype(np.int64)
            fits_t = np.all(alloc64 - ovh_p[None, :] - vec64[None, :] >= 0, axis=1)
            if prov_pods_cap is not None:
                fits_t &= (prov_pods_cap[pi].astype(np.int64)
                           - ovh_p[pods_i] - vec64[pods_i] >= 0)
        base = fold_option_mask(reqs, cols, prov).reshape(T, S) & fits_t[:, None]
        prefs: "list[np.ndarray]" = []
        if base.any() and group.spec.preferences:
            # empty base can only stay empty under availability ANDs, so
            # prefix folds would never be consulted — skip them (the old
            # one-shot encode gated the relaxation the same way)
            # iterative preference relaxation — mirrors the oracle's
            # feasible_options exactly (PodSpec.preferences docstring):
            # largest satisfiable prefix of weight-ordered terms wins;
            # satisfiability depends on availability, so the prefix masks
            # are stored and the CHOICE happens in combine_group
            for k in range(len(group.spec.preferences), 0, -1):
                try:
                    pref_reqs = reqs
                    for term in group.spec.preferences[:k]:
                        pref_reqs = pref_reqs.union(term)
                except IncompatibleError:
                    continue
                prefs.append(fold_option_mask(pref_reqs, cols, prov)
                             .reshape(T, S) & fits_t[:, None])
        per_prov.append((pi, base, prefs))
    return GroupStatic(vec, cap, len(provs), per_prov)


def combine_group(
    static: GroupStatic, avail: np.ndarray,
) -> "tuple[np.ndarray, int, np.ndarray, int]":
    """Fold current availability (grid.valid, optionally ∧ an extra option
    mask) into a static group encode -> (vec, cap, feas [Pv,T,S], newprov)."""
    T, S = avail.shape
    feas = np.zeros((static.n_provs, T, S), dtype=bool)
    newprov = -1
    for pi, base, prefs in static.per_prov:
        mask = base & avail
        if mask.any() and prefs:
            for pm in prefs:  # k-descending; largest satisfiable prefix wins
                m2 = pm & avail
                if m2.any():
                    mask = m2
                    break
        if mask.any():
            feas[pi] = mask
            if newprov < 0:
                newprov = pi
    return static.vec, static.cap, feas, newprov


def diagnose_unschedulable(
    pod: PodSpec,
    provisioners: "Sequence[Provisioner]",
    catalog: Catalog,
    daemon_overhead: Optional[Sequence[int]] = None,
    grid: Optional[OptionGrid] = None,
    kubelet: "Optional[tuple]" = None,
    option_mask: Optional[np.ndarray] = None,
) -> str:
    """WHY a pod cannot schedule, as a human-readable clause for the
    FailedScheduling event — the reference's scheduler errors name the
    failing constraint ("incompatible with provisioner …", "no instance
    type satisfied resources …") rather than a generic message. Walks the
    admission rule's stages in order and reports the first one no
    provisioner survives."""
    if grid is None or grid.seqnum != catalog.seqnum:
        grid = build_grid(catalog, reuse=grid)
    provs = list(provisioners)  # flags are ORed: order is irrelevant
    cols = grid.get_cols()
    overhead = list(daemon_overhead or [0] * wk.NUM_RESOURCES)
    group = PodGroup(spec=pod, count=1, pod_names=[pod.name])
    vec64 = np.minimum(group.vector, INT_BIG).astype(np.int64)
    ovh = np.asarray(overhead, dtype=np.int64)
    alloc64 = grid.alloc_t.astype(np.int64)
    # kubelet arrays are O(Pv*T) Python to build: callers diagnosing many
    # groups per cycle pass them in once (indexed by position in `provs`)
    prov_overhead, prov_pods_cap = (
        kubelet if kubelet is not None else kubelet_arrays(provs, catalog))
    any_tol = any_req = any_fit = any_avail = any_divers = False
    eff_valid = grid.valid if option_mask is None \
        else (grid.valid & option_mask)
    for pi, prov in enumerate(provs):
        if not tolerates_all(pod.tolerations, prov.taints):
            continue
        any_tol = True
        try:
            reqs = prov.scheduling_requirements().union(pod.requirements)
        except IncompatibleError:
            continue
        req_mask = fold_option_mask(reqs, cols, prov).reshape(grid.T, grid.S)
        if not req_mask.any():
            continue
        any_req = True
        ovh_p = ovh if prov_overhead is None \
            else ovh + prov_overhead[pi].astype(np.int64)
        fits_t = np.all(alloc64 - ovh_p[None, :] - vec64[None, :] >= 0, axis=1)
        if prov_pods_cap is not None:
            pods_i = wk.RESOURCE_INDEX[wk.RESOURCE_PODS]
            fits_t &= (prov_pods_cap[pi].astype(np.int64)
                       - ovh_p[pods_i] - vec64[pods_i] >= 0)
        m = req_mask & fits_t[:, None]
        if not m.any():
            continue
        any_fit = True
        if (m & grid.valid).any():
            any_avail = True
            if (m & eff_valid).any():
                any_divers = True
    if not any_tol:
        return "pod does not tolerate the taints of any provisioner"
    if not any_req:
        return ("pod requirements are incompatible with every "
                "provisioner and instance type")
    if not any_fit:
        return "resource requests do not fit any compatible instance type"
    if not any_avail:
        return ("every compatible offering is currently unavailable "
                "(insufficient capacity)")
    if not any_divers:
        return ("every remaining compatible offering is barred by the spot "
                "diversity floor this cycle")
    # option-level admission passes; the failure is cross-pod (affinity /
    # topology caps / provisioner limits interplay) this cycle
    return ("compatible capacity exists but scheduling constraints "
            "(affinity/topology/limits) were unsatisfiable this cycle")


def encode_group(
    group: PodGroup,
    provs: "list[Provisioner]",
    grid: OptionGrid,
    cols: GridCols,
    overhead: Sequence[int],
    extra_mask: Optional[np.ndarray] = None,
    prov_overhead: Optional[np.ndarray] = None,
    prov_pods_cap: Optional[np.ndarray] = None,
) -> "tuple[np.ndarray, int, np.ndarray, int]":
    """One pod group -> (vec [R], cap, feas [Pv,T,S], newprov).

    The single source of the admission rule (tolerations ∧ requirements ∧
    fresh-node capacity ∧ availability ∧ optional extra option mask) shared
    by provisioning (encode_problem) and consolidation (ops/consolidate.py)
    — the two must stay bit-identical for kernel/oracle parity."""
    static = encode_group_static(group, provs, grid, cols, overhead,
                                 prov_overhead=prov_overhead,
                                 prov_pods_cap=prov_pods_cap)
    avail = grid.valid if extra_mask is None else (grid.valid & extra_mask)
    return combine_group(static, avail)


def _ex_label_fit(e: ExistingNode, spec: PodSpec) -> bool:
    """Label/taint feasibility of an existing node, capacity excluded (the
    kernel handles capacity)."""
    from ..models.pod import tolerates_all

    return (tolerates_all(spec.tolerations, e.taints)
            and spec.requirements.matches_labels(e.effective_labels()))


def fold_node_mask(reqs: Requirements, lookup, n: int) -> np.ndarray:
    """Requirements -> bool mask over node rows. Vectorized equivalent of
    `reqs.matches_labels(labels_of_row)` per row (the node-axis twin of
    fold_option_mask — no provisioner overlay; whether hostname defaults to
    the node name is the caller's choice of `lookup`).

    `lookup(key)` returns (codes [i32 n], num [f64 n], vocab) with -1/nan for
    rows lacking the key, or None when no row anywhere carries the key.
    Checked against matches_labels property-test-style in
    tests/test_columnar_state.py."""
    mask = np.ones(n, dtype=bool)
    for req in reqs:
        col = lookup(req.key)
        if col is None:
            if not req.allows_absent():
                return np.zeros(n, dtype=bool)
            continue
        codes, num, vocab = col
        present = codes >= 0
        if req.forbid_key:
            mask &= ~present
            continue
        value_codes = [vocab[v] for v in req.values if v in vocab]
        hits = np.isin(codes, value_codes) if value_codes \
            else np.zeros(n, dtype=bool)
        ok_present = ~hits if req.complement else hits
        if req.gt is not None or req.lt is not None:
            with np.errstate(invalid="ignore"):
                if req.gt is not None:
                    ok_present &= num > req.gt
                if req.lt is not None:
                    ok_present &= num < req.lt
        mask &= np.where(present, ok_present, req.allows_absent())
    return mask


def existing_fit_vector(ex: "ExistingColumns", spec: PodSpec) -> np.ndarray:
    """Columnar `_ex_label_fit`: one [Ne] bool vector per group spec, folded
    over the snapshot's label-code columns (hostname defaulted to node name,
    effective_labels() semantics) and the interned taint-set codes — each
    distinct taint set is checked against the tolerations once, not per node.
    Memoized per (snapshot, spec)."""
    cached = ex._fit_cache.get(id(spec))
    if cached is not None and cached[0] is spec:
        return cached[1]
    n = len(ex)
    mask = fold_node_mask(spec.requirements, ex.label_lookup, n)
    codes = ex.taint_codes
    for code in np.unique(codes):
        taints = ex.taint_set_of(int(code))
        if taints and not tolerates_all(spec.tolerations, taints):
            mask = mask & (codes != code)
    ex._fit_cache[id(spec)] = (spec, mask)
    return mask
