"""Machine: the capacity-request object.

Parity target: `v1alpha5.Machine` — Spec{Requirements, Resources, Kubelet,
Taints, StartupTaints, MachineTemplateRef} / Status{ProviderID, Capacity,
Allocatable} consumed at /root/reference/pkg/cloudprovider/cloudprovider.go:
112-136 (Create) and 324-365 (instanceToMachine), plus the core machine
lifecycle (create -> launch -> registration -> initialization).
"""

from __future__ import annotations

import dataclasses
from typing import Optional

from ..apis import wellknown as wk
from .pod import Taint
from .requirements import Requirements

# lifecycle states (core machine lifecycle, SURVEY.md §2.2)
PENDING = "Pending"
LAUNCHED = "Launched"
REGISTERED = "Registered"
INITIALIZED = "Initialized"
TERMINATING = "Terminating"


@dataclasses.dataclass
class MachineSpec:
    requirements: Requirements = dataclasses.field(default_factory=Requirements)
    resource_requests: "dict[str, int]" = dataclasses.field(default_factory=dict)
    taints: "tuple[Taint, ...]" = ()
    startup_taints: "tuple[Taint, ...]" = ()
    machine_template_ref: str = ""  # NodeTemplate name
    provisioner_name: str = ""
    # full kubelet config (Machine.Spec.Kubelet): shapes the node's reported
    # allocatable at launch (cloudprovider._instance_to_machine) and the
    # bootstrap kubelet flags (providers/images.py BootstrapConfig)
    kubelet: "Optional[object]" = None  # apis.provisioner.KubeletConfiguration


@dataclasses.dataclass
class MachineStatus:
    provider_id: str = ""
    instance_type: str = ""
    zone: str = ""
    capacity_type: str = ""
    image_id: str = ""
    capacity: "dict[str, int]" = dataclasses.field(default_factory=dict)
    allocatable: "dict[str, int]" = dataclasses.field(default_factory=dict)
    state: str = PENDING
    node_name: str = ""
    price: float = 0.0


@dataclasses.dataclass
class Machine:
    name: str
    spec: MachineSpec = dataclasses.field(default_factory=MachineSpec)
    status: MachineStatus = dataclasses.field(default_factory=MachineStatus)
    labels: "dict[str, str]" = dataclasses.field(default_factory=dict)
    annotations: "dict[str, str]" = dataclasses.field(default_factory=dict)
    deleted: bool = False

    def launched(self) -> bool:
        return self.status.state in (LAUNCHED, REGISTERED, INITIALIZED)


def parse_provider_id(provider_id: str) -> "tuple[str, str]":
    """'tpu:///<zone>/<instance-id>' -> (zone, id)
    (reference: `aws:///<az>/<id>` regex parse, pkg/utils/utils.go:21-39)."""
    prefix = "tpu:///"
    if not provider_id.startswith(prefix):
        raise ValueError(f"invalid provider id {provider_id!r}")
    rest = provider_id[len(prefix):]
    zone, _, iid = rest.partition("/")
    if not zone or not iid:
        raise ValueError(f"invalid provider id {provider_id!r}")
    return zone, iid


def make_provider_id(zone: str, instance_id: str) -> str:
    return f"tpu:///{zone}/{instance_id}"
