"""In-memory cluster state, columnar (struct-of-arrays) form.

Parity target: karpenter-core's `state.Cluster` (consumed at
/root/reference/cmd/controller/main.go:54) — the node/pod/machine snapshot the
scheduler and deprovisioner read. State is rebuildable from the cluster+cloud
(reference checkpoint story, SURVEY.md §5.4): nothing here is persisted.

Layout (docs/designs/columnar-state.md): `ClusterState` keeps the dataclass
surface (`StateNode`, `nodes` dict, `pdbs` list) every existing consumer and
test speaks, but mirrors it into a `ColumnarCluster` of contiguous numpy
columns — allocatable/used resource rows, price/created/flag scalars,
interned per-key label codes, interned taint-set codes — maintained
*incrementally*: `bind_pod`, `delete_node`, and every watch delta update the
columns and a per-row change sequence in O(1) amortized, never rescanning.
The hot consumers (provisioning mask construction, deprovisioning sweeps,
solver host-encode) read the columns; the dataclasses remain the
compatibility view.

Synchronization contract: same as the dict-based state this replaces — the
GIL makes individual column writes atomic, and the operator already
serializes whole reconcile passes against watch appliers exactly as it did
before (no new locking is introduced, and no new races either: a torn read
across columns corresponds to the torn read across `StateNode` fields the
legacy views had).
"""

from __future__ import annotations

import bisect
import collections
import dataclasses
import threading
from typing import Iterable, Optional

import numpy as np

from ..apis import wellknown as wk
from .pod import PodSpec, Taint

# node-level consolidation veto (kubectl-settable, reference
# deprovisioning.md); lives here so both the columnar mirror and the oracle
# read one constant without an import cycle
ANNOTATION_DO_NOT_CONSOLIDATE = "karpenter.sh/do-not-consolidate"

_CPU = wk.RESOURCE_INDEX[wk.RESOURCE_CPU]
_MEM = wk.RESOURCE_INDEX[wk.RESOURCE_MEMORY]
_R = wk.NUM_RESOURCES

# StateNode fields whose writes must reach the columns; everything else goes
# through the fast plain-setattr path
_TRACKED_FIELDS = frozenset((
    "pods", "labels", "annotations", "allocatable", "taints",
    "provisioner_name", "price", "created_ts", "initialized",
    "marked_for_deletion", "drifted",
))


class _SyncedDict(dict):
    """Dict that tells its owning node when mutated in place (tests and the
    veto surface poke `node.annotations[...]` / `node.labels[...]` directly;
    the columns must not go stale underneath them)."""

    __slots__ = ("_node", "_field")

    def _sync(self):
        node = getattr(self, "_node", None)
        if node is not None:
            node._dict_changed(self._field)

    def __setitem__(self, k, v):
        super().__setitem__(k, v)
        self._sync()

    def __delitem__(self, k):
        super().__delitem__(k)
        self._sync()

    def update(self, *a, **kw):
        super().update(*a, **kw)
        self._sync()

    def pop(self, *a):
        out = super().pop(*a)
        self._sync()
        return out

    def popitem(self):
        out = super().popitem()
        self._sync()
        return out

    def clear(self):
        super().clear()
        self._sync()

    def setdefault(self, k, d=None):
        out = super().setdefault(k, d)
        self._sync()
        return out


class _PodList(list):
    """Pod list that keeps the node's incremental aggregates (used vector,
    non-daemon count, resident group counts) in sync on every mutation, so
    `used_vector()` is O(R) instead of O(pods x R)."""

    __slots__ = ("_node",)

    def _delta(self, added, removed):
        node = getattr(self, "_node", None)
        if node is not None:
            node._pods_delta(added, removed)

    def append(self, pod):
        super().append(pod)
        self._delta((pod,), ())

    def extend(self, pods):
        pods = list(pods)
        super().extend(pods)
        self._delta(pods, ())

    def insert(self, i, pod):
        super().insert(i, pod)
        self._delta((pod,), ())

    def remove(self, pod):
        super().remove(pod)
        self._delta((), (pod,))

    def pop(self, i=-1):
        pod = super().pop(i)
        self._delta((), (pod,))
        return pod

    def clear(self):
        old = list(self)
        super().clear()
        self._delta((), old)

    def __setitem__(self, i, value):
        if isinstance(i, slice):
            old = self[i]
            value = list(value)
            super().__setitem__(i, value)
            self._delta(value, old)
        else:
            old = self[i]
            super().__setitem__(i, value)
            self._delta((value,), (old,))

    def __delitem__(self, i):
        old = self[i] if isinstance(i, slice) else (self[i],)
        super().__delitem__(i)
        self._delta((), old)

    def __iadd__(self, pods):
        self.extend(pods)
        return self

    def __imul__(self, n):  # pragma: no cover - not used; full recount
        out = super().__imul__(n)
        node = getattr(self, "_node", None)
        if node is not None:
            node._recount_pods()
            node._notify_pods_rewritten(())
        return out


@dataclasses.dataclass
class StateNode:
    """One launched node plus its resident pods.

    Attribute writes and in-place pod/label/annotation mutations are
    intercepted (`__setattr__`, `_PodList`, `_SyncedDict`) and mirrored into
    the owning `ClusterState`'s columns; a detached node (never added, or
    already deleted) behaves exactly like the plain dataclass did.
    """

    name: str
    labels: "dict[str, str]"
    allocatable: "list[int]"  # canonical resource axis
    provider_id: str = ""
    provisioner_name: str = ""
    instance_type: str = ""
    zone: str = ""
    capacity_type: str = ""
    price: float = 0.0
    taints: "tuple[Taint, ...]" = ()
    # startup taints registered at boot, cleared at initialization
    # (v1alpha5 startupTaints; the scheduler's in-flight model ignores them)
    startup_taints: "tuple[Taint, ...]" = ()
    pods: "list[PodSpec]" = dataclasses.field(default_factory=list)
    created_ts: float = 0.0
    initialized: bool = True
    machine_name: str = ""
    # karpenter.sh/do-not-consolidate (and future node-level knobs):
    # kubectl-settable veto surface, reference deprovisioning.md
    annotations: "dict[str, str]" = dataclasses.field(default_factory=dict)
    marked_for_deletion: bool = False
    deletion_requested_ts: float = 0.0
    drifted: bool = False

    def __setattr__(self, name, value):
        if name not in _TRACKED_FIELDS:
            object.__setattr__(self, name, value)
            return
        if name == "pods":
            old = self.__dict__.get("pods")
            if value is old:
                return
            wrapped = _PodList(value if value is not None else ())
            wrapped._node = self
            object.__setattr__(self, "pods", wrapped)
            self._recount_pods()
            self._notify_pods_rewritten(old if old is not None else ())
            return
        if name in ("labels", "annotations") and not (
                isinstance(value, _SyncedDict)
                and getattr(value, "_node", None) is self):
            synced = _SyncedDict(value if value is not None else ())
            synced._node = self
            synced._field = name
            value = synced
        old = self.__dict__.get(name)
        object.__setattr__(self, name, value)
        owner = self.__dict__.get("_owner")
        if owner is not None:
            owner._node_field_changed(self, name, old)

    # -- incremental aggregates ---------------------------------------------------

    def _recount_pods(self) -> None:
        used = [0] * _R
        non_daemon = 0
        resident: "dict[tuple, int]" = {}
        prefs: "set[str]" = set()
        for p in self.pods:
            vec = p.resource_vector()
            for i in range(_R):
                used[i] += vec[i]
            if p.owner_kind != "DaemonSet":
                non_daemon += 1
                k = p.group_key()
                resident[k] = resident.get(k, 0) + 1
                if p.preferences:
                    prefs.add(p.name)
        object.__setattr__(self, "_used", used)
        object.__setattr__(self, "_non_daemon", non_daemon)
        object.__setattr__(self, "_resident_counts", resident)
        object.__setattr__(self, "_pref_names", prefs)

    def _pods_delta(self, added, removed) -> None:
        used = self._used
        resident = self._resident_counts
        non_daemon = self._non_daemon
        prefs = self._pref_names
        for sign, pods in ((-1, removed), (1, added)):
            for p in pods:
                vec = p.resource_vector()
                for i in range(_R):
                    used[i] += sign * vec[i]
                if p.owner_kind != "DaemonSet":
                    non_daemon += sign
                    k = p.group_key()
                    n = resident.get(k, 0) + sign
                    if n:
                        resident[k] = n
                    else:
                        resident.pop(k, None)
                    if p.preferences:
                        if sign > 0:
                            prefs.add(p.name)
                        else:
                            prefs.discard(p.name)
        object.__setattr__(self, "_non_daemon", non_daemon)
        owner = self.__dict__.get("_owner")
        if owner is not None:
            owner._node_pods_delta(self, added, removed)

    def _notify_pods_rewritten(self, old_pods) -> None:
        owner = self.__dict__.get("_owner")
        if owner is not None:
            owner._node_pods_replaced(self, old_pods)

    def _dict_changed(self, field: str) -> None:
        owner = self.__dict__.get("_owner")
        if owner is not None:
            owner._node_field_changed(self, field, None)

    # -- views --------------------------------------------------------------------

    def used_vector(self) -> "list[int]":
        return list(self._used)

    def non_daemon_pods(self) -> "list[PodSpec]":
        return [p for p in self.pods if not p.is_daemon()]

    def is_empty(self) -> bool:
        return self._non_daemon == 0

    def to_existing(self):
        """ExistingNode view for the scheduler (used capacity included)."""
        from ..oracle.scheduler import ExistingNode

        return ExistingNode(
            name=self.name,
            labels=dict(self.labels),
            allocatable=list(self.allocatable),
            used=self.used_vector(),
            taints=self.taints,
            resident=tuple(self.non_daemon_pods()),
        )


@dataclasses.dataclass
class PodDisruptionBudget:
    """Minimal PDB model: blocks eviction when disruptionsAllowed == 0
    (designs/consolidation.md 'Pods that Prevent Consolidation')."""

    name: str
    selector: "dict[str, str]"
    min_available: Optional[int] = None
    max_unavailable: Optional[int] = None

    def matches(self, pod: PodSpec) -> bool:
        labels = dict(pod.labels)
        return all(labels.get(k) == v for k, v in self.selector.items())

    def disruptions_allowed(self, matching_healthy: int) -> int:
        if self.min_available is not None:
            return max(0, matching_healthy - self.min_available)
        if self.max_unavailable is not None:
            return max(0, self.max_unavailable)
        return matching_healthy


def _selector_matches(selector: "dict[str, str]",
                      labels: "dict[str, str]") -> bool:
    return all(labels.get(k) == v for k, v in selector.items())


class PDBIndex:
    """PDBs bucketed by one representative selector item.

    A pod matching a PDB must carry EVERY (k, v) of the selector, so bucketing
    each PDB under a single representative item and probing with the pod's own
    label items yields a candidate superset; a full `matches()` check on the
    candidates gives semantics identical to scanning every PDB
    (tests/test_columnar_state.py parity suite). Empty selectors match
    everything and live in their own always-probed bucket.
    """

    def __init__(self, pdbs: "list[PodDisruptionBudget]"):
        self.pdbs = list(pdbs)
        self.by_item: "dict[tuple[str, str], list[int]]" = {}
        self.empty: "list[int]" = []
        for pos, pdb in enumerate(self.pdbs):
            if pdb.selector:
                rep = min(pdb.selector.items())
                self.by_item.setdefault(rep, []).append(pos)
            else:
                self.empty.append(pos)

    def candidate_positions(self, labels: "dict[str, str]") -> "list[int]":
        out = list(self.empty)
        if self.by_item:
            for item in labels.items():
                hit = self.by_item.get(item)
                if hit:
                    out.extend(hit)
        return out

    def matching_positions(self, labels: "dict[str, str]") -> "list[int]":
        return [pos for pos in self.candidate_positions(labels)
                if _selector_matches(self.pdbs[pos].selector, labels)]


class _KeyColumn:
    """One label key's interned value column: codes[row] (-1 = absent) plus a
    numeric shadow for Gt/Lt folds (nan = absent or non-integer)."""

    __slots__ = ("codes", "num", "vocab")

    def __init__(self, capacity: int):
        self.codes = np.full(capacity, -1, dtype=np.int32)
        self.num = np.full(capacity, np.nan, dtype=np.float64)
        self.vocab: "dict[str, int]" = {}

    def grow(self, capacity: int) -> None:
        codes = np.full(capacity, -1, dtype=np.int32)
        codes[: len(self.codes)] = self.codes
        self.codes = codes
        num = np.full(capacity, np.nan, dtype=np.float64)
        num[: len(self.num)] = self.num
        self.num = num

    def set(self, row: int, value: str) -> None:
        code = self.vocab.get(value)
        if code is None:
            code = self.vocab[value] = len(self.vocab)
        self.codes[row] = code
        try:
            # mirror Requirement.has(): int() parse, not float()
            self.num[row] = float(int(value))
        except ValueError:
            self.num[row] = np.nan

    def clear(self, row: int) -> None:
        self.codes[row] = -1
        self.num[row] = np.nan


class ColumnarCluster:
    """Struct-of-arrays mirror of the node set.

    Rows are interned node slots (freelist-recycled); every column is a
    contiguous numpy array over the same row space, so the reconcile sweeps
    (emptiness, expiration, consolidation prefilter) and the solver's
    existing-node encode are single vectorized expressions instead of per-node
    Python. `changed_seq[row]` carries the cluster-wide mutation sequence of
    the row's last change — the dirty-set the deprovisioner keys its
    incremental re-evaluation off.
    """

    def __init__(self, capacity: int = 64):
        self.capacity = capacity
        self.row_of: "dict[str, int]" = {}
        self.name_of: "list[Optional[str]]" = [None] * capacity
        self._free = list(range(capacity - 1, -1, -1))
        self.alloc = np.zeros((capacity, _R), dtype=np.int64)
        self.used = np.zeros((capacity, _R), dtype=np.int64)
        self.price = np.zeros(capacity, dtype=np.float64)
        self.created_ts = np.zeros(capacity, dtype=np.float64)
        self.occupied = np.zeros(capacity, dtype=bool)
        self.marked = np.zeros(capacity, dtype=bool)
        self.initialized = np.zeros(capacity, dtype=bool)
        self.drifted = np.zeros(capacity, dtype=bool)
        self.no_consolidate = np.zeros(capacity, dtype=bool)
        self.non_daemon = np.zeros(capacity, dtype=np.int64)
        self.prov_code = np.full(capacity, -1, dtype=np.int32)
        self.taint_code = np.zeros(capacity, dtype=np.int32)
        self.changed_seq = np.zeros(capacity, dtype=np.int64)
        self.label_cols: "dict[str, _KeyColumn]" = {}
        # LABEL_HOSTNAME defaulted to the node name when absent — the
        # effective_labels() convention the scheduler's label fit uses
        self.eff_hostname = _KeyColumn(capacity)
        self.prov_names: "list[str]" = []
        self._prov_vocab: "dict[str, int]" = {}
        self.taint_sets: "list[tuple[Taint, ...]]" = [()]
        self._taint_vocab: "dict[tuple[Taint, ...], int]" = {(): 0}

    def _grow(self) -> None:
        old = self.capacity
        cap = self.capacity = old * 2
        self.name_of.extend([None] * old)
        self._free.extend(range(cap - 1, old - 1, -1))
        for attr in ("price", "created_ts", "occupied", "marked",
                     "initialized", "drifted", "no_consolidate",
                     "non_daemon", "changed_seq"):
            col = getattr(self, attr)
            grown = np.zeros(cap, dtype=col.dtype)
            grown[:old] = col
            setattr(self, attr, grown)
        for attr, fill in (("prov_code", -1), ("taint_code", 0)):
            col = getattr(self, attr)
            grown = np.full(cap, fill, dtype=col.dtype)
            grown[:old] = col
            setattr(self, attr, grown)
        for attr in ("alloc", "used"):
            col = getattr(self, attr)
            grown = np.zeros((cap, _R), dtype=col.dtype)
            grown[:old] = col
            setattr(self, attr, grown)
        for kc in self.label_cols.values():
            kc.grow(cap)
        self.eff_hostname.grow(cap)

    def acquire(self, name: str) -> int:
        if not self._free:
            self._grow()
        row = self._free.pop()
        self.row_of[name] = row
        self.name_of[row] = name
        self.occupied[row] = True
        return row

    def release(self, name: str, label_keys: "tuple[str, ...]") -> None:
        row = self.row_of.pop(name)
        self.name_of[row] = None
        self.occupied[row] = False
        self.marked[row] = False
        self.initialized[row] = False
        self.drifted[row] = False
        self.no_consolidate[row] = False
        self.non_daemon[row] = 0
        self.alloc[row] = 0
        self.used[row] = 0
        self.price[row] = 0.0
        self.created_ts[row] = 0.0
        self.prov_code[row] = -1
        self.taint_code[row] = 0
        for key in label_keys:
            kc = self.label_cols.get(key)
            if kc is not None:
                kc.clear(row)
        self.eff_hostname.clear(row)
        self._free.append(row)

    def label_col(self, key: str) -> _KeyColumn:
        kc = self.label_cols.get(key)
        if kc is None:
            kc = self.label_cols[key] = _KeyColumn(self.capacity)
        return kc

    def intern_provisioner(self, name: str) -> int:
        code = self._prov_vocab.get(name)
        if code is None:
            code = self._prov_vocab[name] = len(self.prov_names)
            self.prov_names.append(name)
        return code

    def intern_taints(self, taints: "tuple[Taint, ...]") -> int:
        taints = tuple(taints)
        code = self._taint_vocab.get(taints)
        if code is None:
            code = self._taint_vocab[taints] = len(self.taint_sets)
            self.taint_sets.append(taints)
        return code

    def set_labels(self, row: int, labels: "dict[str, str]",
                   old_keys: "tuple[str, ...]", node_name: str) -> "tuple[str, ...]":
        for key in old_keys:
            if key not in labels:
                kc = self.label_cols.get(key)
                if kc is not None:
                    kc.clear(row)
        for key, value in labels.items():
            self.label_col(key).set(row, value)
        self.eff_hostname.set(
            row, labels.get(wk.LABEL_HOSTNAME, node_name))
        return tuple(labels.keys())


class ExistingColumns:
    """Columnar snapshot of the schedulable (unmarked) nodes, name-sorted.

    Dual personality: a Sequence of `ExistingNode` for every legacy consumer
    (the oracle scheduler, wire serialization, round-2 carry), AND direct
    column access (`alloc_rows` / `used_rows` / `label_lookup` /
    `taint_codes`) for the vectorized encode fast path
    (models/encode.py fold_node_mask / existing_fit_vector). Views and their
    `resident` tuples materialize lazily, so a 100k-node snapshot that only
    feeds the columnar encode never builds a single per-node dataclass.

    Resource rows are gathered eagerly at snapshot time — the same
    point-in-time copy semantics `existing_views()` had.
    """

    def __init__(self, cluster: "ClusterState", names: "list[str]",
                 rows: "np.ndarray"):
        self.cluster = cluster
        self.names = names
        self.rows = rows
        cols = cluster.columns
        self._nodes = [cluster.nodes[n] for n in names]
        if len(rows):
            self.alloc_rows = cols.alloc[rows]
            self.used_rows = cols.used[rows]
            self.taint_codes = cols.taint_code[rows]
        else:
            self.alloc_rows = np.zeros((0, _R), dtype=np.int64)
            self.used_rows = np.zeros((0, _R), dtype=np.int64)
            self.taint_codes = np.zeros(0, dtype=np.int32)
        self._views: "dict[int, object]" = {}
        self._label_gather: "dict[str, Optional[tuple]]" = {}
        self._fit_cache: "dict[int, tuple]" = {}

    def __len__(self) -> int:
        return len(self.names)

    def __iter__(self):
        for i in range(len(self.names)):
            yield self._view(i)

    def __getitem__(self, i):
        if isinstance(i, slice):
            return [self._view(j) for j in range(*i.indices(len(self.names)))]
        if i < 0:
            i += len(self.names)
        if not 0 <= i < len(self.names):
            raise IndexError(i)
        return self._view(i)

    def _view(self, i: int):
        view = self._views.get(i)
        if view is None:
            from ..oracle.scheduler import ExistingNode

            node = self._nodes[i]
            view = self._views[i] = ExistingNode(
                name=node.name,
                labels=dict(node.labels),
                allocatable=[int(x) for x in self.alloc_rows[i]],
                used=[int(x) for x in self.used_rows[i]],
                taints=node.taints,
                resident=_LazyResident(node),
                resident_counts=dict(node._resident_counts),
            )
        return view

    def materialized_view(self, i: int):
        """Already-built view or None (encode's cap path reads group_counts
        only off views someone else materialized)."""
        return self._views.get(i)

    def label_lookup(self, key: str) -> "Optional[tuple]":
        """(codes[i32 Ne], num[f64 Ne], vocab) for a label key, hostname
        defaulted to the node name; None when no node ever carried the key."""
        out = self._label_gather.get(key, False)
        if out is False:
            cols = self.cluster.columns
            kc = (cols.eff_hostname if key == wk.LABEL_HOSTNAME
                  else cols.label_cols.get(key))
            if kc is None:
                out = None
            elif len(self.rows):
                out = (kc.codes[self.rows], kc.num[self.rows], kc.vocab)
            else:
                out = (np.zeros(0, dtype=np.int32), np.zeros(0), kc.vocab)
            self._label_gather[key] = out
        return out

    def taint_set_of(self, code: int) -> "tuple[Taint, ...]":
        return self.cluster.columns.taint_sets[code]

    def resident_count_vector(self, origin_key) -> "np.ndarray":
        """Per-node resident count of one pod group (zone-spread / cap
        accounting) straight from the incremental node aggregates."""
        return np.fromiter(
            (n._resident_counts.get(origin_key, 0) for n in self._nodes),
            dtype=np.int64, count=len(self._nodes))


class _LazyResident:
    """tuple-compatible lazy view of a node's non-daemon pods: iteration,
    len, indexing, and concatenation all materialize on first touch, so
    snapshots of pod-heavy nodes cost nothing until an affinity term or the
    oracle actually reads residents."""

    __slots__ = ("_node", "_tuple")

    def __init__(self, node: "StateNode"):
        self._node = node
        self._tuple = None

    def _materialize(self) -> tuple:
        out = self._tuple
        if out is None:
            out = self._tuple = tuple(
                p for p in self._node.pods if not p.is_daemon())
        return out

    def __iter__(self):
        return iter(self._materialize())

    def __len__(self):
        return len(self._materialize())

    def __getitem__(self, i):
        return self._materialize()[i]

    def __bool__(self):
        return bool(self._materialize())

    def __add__(self, other):
        return self._materialize() + tuple(other)

    def __radd__(self, other):
        return tuple(other) + self._materialize()

    def __eq__(self, other):
        if isinstance(other, _LazyResident):
            other = other._materialize()
        return self._materialize() == tuple(other)

    def __repr__(self):
        return f"_LazyResident({self._materialize()!r})"


class ClusterState:
    """Mutable cluster snapshot; the deprovisioner and scheduler read this.

    Incremental invariants (chaos-checked, chaos/invariants.py
    check_columnar_coherence):
      * columns.used[row] == sum of the row's pod resource vectors
      * _prov_totals[p] == the full allocatable scan for provisioner p
      * _pdb_counts[i] == pods matching pdbs[i] across ALL nodes and pods
      * sorted name list == sorted(nodes)
    """

    def __init__(self):
        self.nodes: "dict[str, StateNode]" = {}
        self._pdbs: "list[PodDisruptionBudget]" = []
        # instance-id -> node name, maintained incrementally so interruption
        # handling is O(1) per message instead of rebuilding the map per poll
        # (the reference rebuilds per reconcile, controller.go:236-255 — at
        # 15k nodes that rebuild dominates; an indexed view is the same
        # versioned-state trick as the device-resident catalog)
        self._by_instance_id: "dict[str, str]" = {}
        self.columns = ColumnarCluster()
        self._sorted_names: "list[str]" = []
        self._seq = 0
        self._prov_totals: "dict[str, list[int]]" = {}
        self._pdb_memo: "Optional[tuple]" = None
        self._pdb_counts: "list[int]" = []
        self._pdb_epoch = 0
        self._healthy_memo: "Optional[dict[str, int]]" = None
        # node name -> (row seq, pdb epoch, all pods evictable, {pdb pos: n})
        self._evict_cache: "dict[str, tuple]" = {}
        # cumulative count of full per-node evictability recomputes — the
        # soak benchmark's "re-evaluated nodes per cycle" reads deltas of this
        self.evict_recomputes = 0
        self._pref_nodes: "dict[str, set[str]]" = {}
        # bounded deletion log for delta consumers: deletions release the
        # row (so changed_seq can't carry them) and only bump _seq. Each
        # entry is (seq-after-bump, name); once the deque evicts, the floor
        # rises and deleted_since() reports cursors below it as incomplete,
        # forcing those consumers to a full resync.
        self._deletion_log: "collections.deque[tuple[int, str]]" = \
            collections.deque(maxlen=4096)
        self._deletion_floor = 0
        # mutator lock: the legacy dict-of-dataclasses state tolerated
        # GIL-interleaved writers (parallel launches call add_node from a
        # thread pool), but the columnar freelist + array-doubling grow do
        # not — a thread popping a just-grown row while another still holds
        # the pre-grow arrays would index out of bounds or lose its write.
        # Readers stay lockless (same snapshot semantics as before: the
        # controllers read between joined launch batches).
        self._lock = threading.RLock()

    # -- pdb surface (list-compatible, index kept coherent) -----------------------

    @property
    def pdbs(self) -> "list[PodDisruptionBudget]":
        return self._pdbs

    @pdbs.setter
    def pdbs(self, value) -> None:
        self._pdbs = value if isinstance(value, list) else list(value)
        self._pdb_memo = None

    # -- identity -----------------------------------------------------------------

    @staticmethod
    def _instance_id(node: StateNode) -> str:
        if not node.provider_id:
            return ""
        return node.provider_id.rsplit("/", 1)[-1]

    @property
    def seq(self) -> int:
        """Cluster-wide mutation sequence (monotone; per-row last-change
        values live in columns.changed_seq)."""
        return self._seq

    def _mark_dirty(self, row: int) -> None:
        self._seq += 1
        self.columns.changed_seq[row] = self._seq

    def dirty_since(self, cursor: int) -> "list[str]":
        """Names of nodes changed after `cursor` (a previously observed
        `seq`), name-sorted. O(rows) numpy compare, no Python per-node work
        until the (small) changed set is gathered."""
        cols = self.columns
        hits = np.nonzero(cols.occupied & (cols.changed_seq > cursor))[0]
        return sorted(cols.name_of[r] for r in hits)

    def deleted_since(self, cursor: int) -> "tuple[list[str], bool]":
        """(names deleted after `cursor`, complete). Deletions release the
        row, so `changed_seq` cannot carry them; they land in a bounded
        log instead. `complete` is False when the cursor predates the log
        horizon (evicted entries) — the caller must treat the whole fleet
        as dirty."""
        if cursor < self._deletion_floor:
            return [], False
        names = sorted({name for seq, name in self._deletion_log
                        if seq > cursor})
        return names, True

    # -- node membership ----------------------------------------------------------

    def add_node(self, node: StateNode) -> None:
        with self._lock:
            self._add_node_locked(node)

    def _add_node_locked(self, node: StateNode) -> None:
        if node.name in self.nodes:
            self.delete_node(node.name)
        self.nodes[node.name] = node
        iid = self._instance_id(node)
        if iid:
            self._by_instance_id[iid] = node.name
        if "_used" not in node.__dict__:  # detached node built before tracking
            node._recount_pods()
        object.__setattr__(node, "_owner", self)
        cols = self.columns
        row = cols.acquire(node.name)
        object.__setattr__(node, "_row", row)
        cols.alloc[row] = node.allocatable
        cols.used[row] = node._used
        cols.price[row] = node.price
        cols.created_ts[row] = node.created_ts
        cols.marked[row] = node.marked_for_deletion
        cols.initialized[row] = node.initialized
        cols.drifted[row] = node.drifted
        cols.no_consolidate[row] = (
            node.annotations.get(ANNOTATION_DO_NOT_CONSOLIDATE) == "true")
        cols.non_daemon[row] = node._non_daemon
        cols.prov_code[row] = cols.intern_provisioner(node.provisioner_name)
        cols.taint_code[row] = cols.intern_taints(node.taints)
        object.__setattr__(
            node, "_label_keys",
            cols.set_labels(row, node.labels, (), node.name))
        bisect.insort(self._sorted_names, node.name)
        totals = self._prov_totals.setdefault(node.provisioner_name, [0, 0])
        totals[0] += node.allocatable[_CPU]
        totals[1] += node.allocatable[_MEM]
        if node.pods:
            self._healthy_swap((), node.pods)
        if node._pref_names:
            self._pref_nodes[node.name] = node._pref_names
        self._mark_dirty(row)

    def delete_node(self, name: str) -> Optional[StateNode]:
        with self._lock:
            return self._delete_node_locked(name)

    def _delete_node_locked(self, name: str) -> Optional[StateNode]:
        node = self.nodes.pop(name, None)
        if node is None:
            return None
        iid = self._instance_id(node)
        if iid and self._by_instance_id.get(iid) == name:
            del self._by_instance_id[iid]
        if node.pods:
            self._healthy_swap(node.pods, ())
        totals = self._prov_totals.get(node.provisioner_name)
        if totals is not None:
            totals[0] -= node.allocatable[_CPU]
            totals[1] -= node.allocatable[_MEM]
        self.columns.release(name, node._label_keys)
        idx = bisect.bisect_left(self._sorted_names, name)
        if idx < len(self._sorted_names) and self._sorted_names[idx] == name:
            self._sorted_names.pop(idx)
        object.__setattr__(node, "_owner", None)
        self._evict_cache.pop(name, None)
        self._pref_nodes.pop(name, None)
        self._seq += 1  # membership change is itself a delta
        log = self._deletion_log
        if log.maxlen is not None and len(log) == log.maxlen:
            self._deletion_floor = log[0][0]
        log.append((self._seq, name))
        return node

    def node_by_instance_id(self, instance_id: str) -> Optional[StateNode]:
        name = self._by_instance_id.get(instance_id)
        return self.nodes.get(name) if name else None

    def bind_pod(self, node_name: str, pod: PodSpec) -> None:
        self.nodes[node_name].pods.append(
            dataclasses.replace(pod, node_name=node_name))

    # -- mutation fan-in from StateNode hooks -------------------------------------

    def _node_field_changed(self, node: StateNode, field: str, old) -> None:
        with self._lock:
            self._node_field_changed_locked(node, field, old)

    def _node_field_changed_locked(self, node: StateNode, field: str,
                                   old) -> None:
        cols = self.columns
        row = node._row
        if field == "price":
            cols.price[row] = node.price
        elif field == "marked_for_deletion":
            cols.marked[row] = node.marked_for_deletion
        elif field == "initialized":
            cols.initialized[row] = node.initialized
        elif field == "drifted":
            cols.drifted[row] = node.drifted
        elif field == "created_ts":
            cols.created_ts[row] = node.created_ts
        elif field == "annotations":
            cols.no_consolidate[row] = (
                node.annotations.get(ANNOTATION_DO_NOT_CONSOLIDATE) == "true")
        elif field == "labels":
            object.__setattr__(
                node, "_label_keys",
                cols.set_labels(row, dict(node.labels),
                                getattr(node, "_label_keys", ()), node.name))
        elif field == "taints":
            cols.taint_code[row] = cols.intern_taints(node.taints)
        elif field == "allocatable":
            totals = self._prov_totals.setdefault(
                node.provisioner_name, [0, 0])
            if old is not None:
                totals[0] -= old[_CPU]
                totals[1] -= old[_MEM]
            totals[0] += node.allocatable[_CPU]
            totals[1] += node.allocatable[_MEM]
            cols.alloc[row] = node.allocatable
        elif field == "provisioner_name":
            if old is not None:
                prev = self._prov_totals.get(old)
                if prev is not None:
                    prev[0] -= node.allocatable[_CPU]
                    prev[1] -= node.allocatable[_MEM]
            totals = self._prov_totals.setdefault(
                node.provisioner_name, [0, 0])
            totals[0] += node.allocatable[_CPU]
            totals[1] += node.allocatable[_MEM]
            cols.prov_code[row] = cols.intern_provisioner(
                node.provisioner_name)
        self._mark_dirty(row)

    def _node_pods_delta(self, node: StateNode, added, removed) -> None:
        with self._lock:
            cols = self.columns
            row = node._row
            cols.used[row] = node._used
            cols.non_daemon[row] = node._non_daemon
            if added or removed:
                self._healthy_swap(removed, added)
            if node._pref_names:
                self._pref_nodes[node.name] = node._pref_names
            else:
                self._pref_nodes.pop(node.name, None)
            self._mark_dirty(row)

    def _node_pods_replaced(self, node: StateNode, old_pods) -> None:
        with self._lock:
            cols = self.columns
            row = node._row
            cols.used[row] = node._used
            cols.non_daemon[row] = node._non_daemon
            if old_pods or node.pods:
                self._healthy_swap(old_pods, node.pods)
            if node._pref_names:
                self._pref_nodes[node.name] = node._pref_names
            else:
                self._pref_nodes.pop(node.name, None)
            self._mark_dirty(row)

    # -- PDB index + incremental healthy counts -----------------------------------

    def _pdb_index(self) -> PDBIndex:
        pdbs = self._pdbs
        key = (id(pdbs), len(pdbs))
        memo = self._pdb_memo
        if memo is not None and memo[0] == key:
            return memo[1]
        index = PDBIndex(pdbs)
        counts = [0] * len(pdbs)
        if pdbs:
            for node in self.nodes.values():
                for p in node.pods:
                    labels = dict(p.labels)
                    for pos in index.matching_positions(labels):
                        counts[pos] += 1
        self._pdb_counts = counts
        self._pdb_memo = (key, index)
        self._pdb_epoch += 1
        self._healthy_memo = None
        self._evict_cache.clear()
        return index

    def _healthy_swap(self, removed, added) -> None:
        """Apply a pod-membership delta to the per-PDB healthy counts. When
        the index itself had to rebuild (stale memo), the rebuild scanned the
        CURRENT pod lists — which already include this delta — so it must not
        be applied again (epoch check)."""
        epoch = self._pdb_epoch
        index = self._pdb_index()
        if self._pdb_epoch != epoch or not index.pdbs:
            return
        counts = self._pdb_counts
        for sign, pods in ((-1, removed), (1, added)):
            for p in pods:
                labels = dict(p.labels)
                for pos in index.matching_positions(labels):
                    counts[pos] += sign
        self._healthy_memo = None

    def pdb_healthy(self) -> "dict[str, int]":
        """pdb name -> cluster-wide matching pod count, the `healthy` map the
        eviction checks consume (duplicate names: last wins, matching the
        legacy dict comprehension)."""
        index = self._pdb_index()
        memo = self._healthy_memo
        if memo is None:
            memo = {}
            for pdb, count in zip(index.pdbs, self._pdb_counts):
                memo[pdb.name] = count
            self._healthy_memo = memo
        return memo

    # -- eviction / consolidation eligibility -------------------------------------

    def _node_evictability(self, node: StateNode) -> "tuple[bool, dict]":
        """(every non-daemon pod is controller-owned and not do-not-evict,
        {pdb position: matching pods on this node}), cached until the node's
        row or the PDB set changes."""
        index = self._pdb_index()
        row = node._row
        seq = int(self.columns.changed_seq[row])
        cached = self._evict_cache.get(node.name)
        if cached is not None and cached[0] == seq \
                and cached[1] == self._pdb_epoch:
            return cached[2], cached[3]
        all_ok = True
        on_node: "dict[int, int]" = {}
        for p in node.pods:
            if p.owner_kind == "DaemonSet":
                continue
            if p.do_not_evict or not p.owner_kind:
                all_ok = False
            labels = dict(p.labels)
            for pos in index.matching_positions(labels):
                on_node[pos] = on_node.get(pos, 0) + 1
        self.evict_recomputes += 1
        self._evict_cache[node.name] = (seq, self._pdb_epoch, all_ok, on_node)
        return all_ok, on_node

    def node_consolidation_clear(self, node: StateNode) -> bool:
        """Every resident non-daemon pod may be evicted: controller-owned,
        not do-not-evict, and every matching PDB keeps headroom for ALL of
        this node's matching pods at once. Equivalent to the per-pod
        `pod_evictable` sweep plus the aggregate headroom check (a matching
        pod implies on_node >= 1, so allowed < on_node subsumes allowed < 1),
        but O(1) when cached."""
        all_ok, on_node = self._node_evictability(node)
        if not all_ok:
            return False
        if on_node:
            index = self._pdb_index()
            healthy = self.pdb_healthy()
            for pos, count in on_node.items():
                pdb = index.pdbs[pos]
                if pdb.disruptions_allowed(
                        healthy.get(pdb.name, 0)) < count:
                    return False
        return True

    def pair_pdb_clear(self, a: StateNode, b: StateNode) -> bool:
        """PDB headroom covers evicting BOTH nodes' matching pods at once."""
        _, on_a = self._node_evictability(a)
        _, on_b = self._node_evictability(b)
        merged = dict(on_a)
        for pos, count in on_b.items():
            merged[pos] = merged.get(pos, 0) + count
        if merged:
            index = self._pdb_index()
            healthy = self.pdb_healthy()
            for pos, count in merged.items():
                pdb = index.pdbs[pos]
                if pdb.disruptions_allowed(
                        healthy.get(pdb.name, 0)) < count:
                    return False
        return True

    def consolidation_candidates(self, candidate_filter=None
                                 ) -> "list[StateNode]":
        """Name-sorted nodes passing the consolidation eligibility gate:
        column prefilter (occupied, unmarked, initialized, non-empty, no
        do-not-consolidate veto) then the cached per-node evictability/PDB
        check. Matches a full `eligible()` sweep exactly, but the per-node
        pod scans only rerun for rows dirtied since their last verdict."""
        cols = self.columns
        mask = (cols.occupied & ~cols.marked & cols.initialized
                & (cols.non_daemon > 0) & ~cols.no_consolidate)
        out = []
        for name in sorted(cols.name_of[r] for r in np.nonzero(mask)[0]):
            node = self.nodes[name]
            # live veto read: tests poke node.annotations[...] in place
            if node.annotations.get(ANNOTATION_DO_NOT_CONSOLIDATE) == "true":
                continue
            if not self.node_consolidation_clear(node):
                continue
            if candidate_filter is not None and not candidate_filter(node):
                continue
            out.append(node)
        return out

    # -- column scans (deprovisioning sweeps) -------------------------------------

    def scan_names(self, *, empty: "Optional[bool]" = None,
                   unmarked: bool = False) -> "list[str]":
        """Name-sorted node names matching vectorized flag predicates."""
        cols = self.columns
        mask = cols.occupied.copy()
        if unmarked:
            mask &= ~cols.marked
        if empty is True:
            mask &= cols.non_daemon == 0
        elif empty is False:
            mask &= cols.non_daemon > 0
        return sorted(cols.name_of[r] for r in np.nonzero(mask)[0])

    def pref_pod_nodes(self) -> "dict[str, set[str]]":
        """node name -> names of resident non-daemon pods carrying scheduling
        preferences (incremental; the consolidation pref-awareness pass reads
        this instead of sweeping every pod)."""
        return self._pref_nodes

    # -- snapshots ----------------------------------------------------------------

    def existing_views(self, exclude: "set[str]" = frozenset()):
        return [n.to_existing() for name, n in sorted(self.nodes.items())
                if name not in exclude and not n.marked_for_deletion]

    def existing_columns(self, exclude: "set[str]" = frozenset()
                         ) -> ExistingColumns:
        """Columnar twin of existing_views(): same nodes, same order, same
        point-in-time resource copies — but per-node dataclass views build
        lazily (see ExistingColumns)."""
        cols = self.columns
        if exclude:
            names = [n for n in self._sorted_names if n not in exclude]
        else:
            names = list(self._sorted_names)
        if names:
            rows = np.fromiter((cols.row_of[n] for n in names),
                               dtype=np.int64, count=len(names))
            keep = ~cols.marked[rows]
            if not keep.all():
                names = [n for n, k in zip(names, keep) if k]
                rows = rows[keep]
        else:
            rows = np.zeros(0, dtype=np.int64)
        return ExistingColumns(self, names, rows)

    def total_usage(self, provisioner_name: str) -> "tuple[int, int]":
        """(cpu_millis, memory_bytes) of allocatable committed to a
        provisioner's nodes (limits enforcement, designs/limits.md).
        O(1): running totals maintained on add/delete/reassign, asserted
        against the full scan in tests and the chaos coherence check."""
        totals = self._prov_totals.get(provisioner_name)
        if totals is None:
            return 0, 0
        return totals[0], totals[1] * 2**20


def pod_evictable(pod: PodSpec, pdbs: "Iterable[PodDisruptionBudget]",
                  peers_healthy: "dict[str, int]",
                  index: "Optional[PDBIndex]" = None) -> bool:
    """Consolidation eligibility per pod (consolidation.md 'Pods that Prevent
    Consolidation'): controller-owned, not do-not-evict, PDB headroom > 0.

    With an index, only the PDBs bucketed under the pod's own label items are
    probed — identical verdicts (PDBIndex is a candidate superset + full
    match), O(labels) instead of O(PDBs) per pod."""
    if pod.do_not_evict:
        return False
    if not pod.owner_kind:  # bare pod without controller
        return False
    if index is not None:
        labels = dict(pod.labels)
        for pos in index.matching_positions(labels):
            pdb = index.pdbs[pos]
            if pdb.disruptions_allowed(
                    peers_healthy.get(pdb.name, 0)) < 1:
                return False
        return True
    for pdb in pdbs:
        if pdb.matches(pod) and pdb.disruptions_allowed(
                peers_healthy.get(pdb.name, 0)) < 1:
            return False
    return True
