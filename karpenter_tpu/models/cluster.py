"""In-memory cluster state.

Parity target: karpenter-core's `state.Cluster` (consumed at
/root/reference/cmd/controller/main.go:54) — the node/pod/machine snapshot the
scheduler and deprovisioner read. State is rebuildable from the cluster+cloud
(reference checkpoint story, SURVEY.md §5.4): nothing here is persisted.
"""

from __future__ import annotations

import dataclasses
from typing import Iterable, Optional

from ..apis import wellknown as wk
from .pod import PodSpec, Taint
from .requirements import Requirements


@dataclasses.dataclass
class StateNode:
    """One launched node plus its resident pods."""

    name: str
    labels: "dict[str, str]"
    allocatable: "list[int]"  # canonical resource axis
    provider_id: str = ""
    provisioner_name: str = ""
    instance_type: str = ""
    zone: str = ""
    capacity_type: str = ""
    price: float = 0.0
    taints: "tuple[Taint, ...]" = ()
    # startup taints registered at boot, cleared at initialization
    # (v1alpha5 startupTaints; the scheduler's in-flight model ignores them)
    startup_taints: "tuple[Taint, ...]" = ()
    pods: "list[PodSpec]" = dataclasses.field(default_factory=list)
    created_ts: float = 0.0
    initialized: bool = True
    machine_name: str = ""
    # karpenter.sh/do-not-consolidate (and future node-level knobs):
    # kubectl-settable veto surface, reference deprovisioning.md
    annotations: "dict[str, str]" = dataclasses.field(default_factory=dict)
    marked_for_deletion: bool = False
    deletion_requested_ts: float = 0.0
    drifted: bool = False

    def used_vector(self) -> "list[int]":
        vec = [0] * wk.NUM_RESOURCES
        for p in self.pods:
            for i, v in enumerate(p.resource_vector()):
                vec[i] += v
        return vec

    def non_daemon_pods(self) -> "list[PodSpec]":
        return [p for p in self.pods if not p.is_daemon()]

    def is_empty(self) -> bool:
        return not self.non_daemon_pods()

    def to_existing(self):
        """ExistingNode view for the scheduler (used capacity included)."""
        from ..oracle.scheduler import ExistingNode

        return ExistingNode(
            name=self.name,
            labels=dict(self.labels),
            allocatable=list(self.allocatable),
            used=self.used_vector(),
            taints=self.taints,
            resident=tuple(self.non_daemon_pods()),
        )


@dataclasses.dataclass
class PodDisruptionBudget:
    """Minimal PDB model: blocks eviction when disruptionsAllowed == 0
    (designs/consolidation.md 'Pods that Prevent Consolidation')."""

    name: str
    selector: "dict[str, str]"
    min_available: Optional[int] = None
    max_unavailable: Optional[int] = None

    def matches(self, pod: PodSpec) -> bool:
        labels = dict(pod.labels)
        return all(labels.get(k) == v for k, v in self.selector.items())

    def disruptions_allowed(self, matching_healthy: int) -> int:
        if self.min_available is not None:
            return max(0, matching_healthy - self.min_available)
        if self.max_unavailable is not None:
            return max(0, self.max_unavailable)
        return matching_healthy


class ClusterState:
    """Mutable cluster snapshot; the deprovisioner and scheduler read this."""

    def __init__(self):
        self.nodes: "dict[str, StateNode]" = {}
        self.pdbs: "list[PodDisruptionBudget]" = []
        # instance-id -> node name, maintained incrementally so interruption
        # handling is O(1) per message instead of rebuilding the map per poll
        # (the reference rebuilds per reconcile, controller.go:236-255 — at
        # 15k nodes that rebuild dominates; an indexed view is the same
        # versioned-state trick as the device-resident catalog)
        self._by_instance_id: "dict[str, str]" = {}

    @staticmethod
    def _instance_id(node: StateNode) -> str:
        if not node.provider_id:
            return ""
        return node.provider_id.rsplit("/", 1)[-1]

    def add_node(self, node: StateNode) -> None:
        self.nodes[node.name] = node
        iid = self._instance_id(node)
        if iid:
            self._by_instance_id[iid] = node.name

    def delete_node(self, name: str) -> Optional[StateNode]:
        node = self.nodes.pop(name, None)
        if node is not None:
            iid = self._instance_id(node)
            if iid and self._by_instance_id.get(iid) == name:
                del self._by_instance_id[iid]
        return node

    def node_by_instance_id(self, instance_id: str) -> Optional[StateNode]:
        name = self._by_instance_id.get(instance_id)
        return self.nodes.get(name) if name else None

    def bind_pod(self, node_name: str, pod: PodSpec) -> None:
        self.nodes[node_name].pods.append(
            dataclasses.replace(pod, node_name=node_name))

    def existing_views(self, exclude: "set[str]" = frozenset()):
        return [n.to_existing() for name, n in sorted(self.nodes.items())
                if name not in exclude and not n.marked_for_deletion]

    def total_usage(self, provisioner_name: str) -> "tuple[int, int]":
        """(cpu_millis, memory_bytes) of allocatable committed to a
        provisioner's nodes (limits enforcement, designs/limits.md)."""
        cpu = mem = 0
        for n in self.nodes.values():
            if n.provisioner_name != provisioner_name:
                continue
            cpu += n.allocatable[wk.RESOURCE_INDEX[wk.RESOURCE_CPU]]
            mem += n.allocatable[wk.RESOURCE_INDEX[wk.RESOURCE_MEMORY]] * 2**20
        return cpu, mem


def pod_evictable(pod: PodSpec, pdbs: "Iterable[PodDisruptionBudget]",
                  peers_healthy: "dict[str, int]") -> bool:
    """Consolidation eligibility per pod (consolidation.md 'Pods that Prevent
    Consolidation'): controller-owned, not do-not-evict, PDB headroom > 0."""
    if pod.do_not_evict:
        return False
    if not pod.owner_kind:  # bare pod without controller
        return False
    for pdb in pdbs:
        if pdb.matches(pod) and pdb.disruptions_allowed(
                peers_healthy.get(pdb.name, 0)) < 1:
            return False
    return True
