"""CloudProvider facade: the contract between the core engine and the cloud.

Parity target: /root/reference/pkg/cloudprovider/cloudprovider.go — the core
`cloudprovider.CloudProvider` interface implementation: Create (:112),
Get (:139), GetInstanceTypes (:171), Delete (:189), IsMachineDrifted (:199),
Hydrate (:221), LivenessProbe, machine<->instance translation
(instanceToMachine :324-365, providerID `tpu:///<zone>/<id>`),
resolveInstanceTypes compatibility filter (:302-321), CA-bundle / kube-DNS
plumbed into bootstrap (:367-396).
"""

from __future__ import annotations

import logging
from typing import Optional, Sequence

from .apis import wellknown as wk
from .apis.nodetemplate import NodeTemplate
from .apis.provisioner import Provisioner
from .apis.settings import Settings
from .cache import UnavailableOfferings
from .fake.cloud import CloudInstance
from .models.instancetype import Catalog, InstanceType
from .models.machine import (
    LAUNCHED, Machine, MachineStatus, make_provider_id, parse_provider_id,
)
from .models.requirements import Requirements
from .providers.images import ImageProvider
from .providers.instance import InstanceProvider
from .providers.instancetypes import InstanceTypeProvider
from .providers.launchtemplate import LaunchTemplateProvider
from .providers.pricing import PricingProvider
from .providers.securitygroup import SecurityGroupProvider
from .providers.subnet import SubnetProvider
from .utils import errors as cloud_errors

log = logging.getLogger("karpenter.cloudprovider")


class CloudProvider:
    """Object tree mirrors cloudprovider.New (cloudprovider.go:76-109)."""

    def __init__(self, cloud, settings: Settings, source_catalog: Catalog,
                 clock=None, resilience=None):
        self.cloud = cloud
        self.settings = settings
        self.resilience = resilience
        self.ice = UnavailableOfferings(clock=clock)
        self.subnets = SubnetProvider(cloud, clock=clock)
        self.security_groups = SecurityGroupProvider(cloud, clock=clock)
        static_prices = {
            (t.name, o.capacity_type, o.zone): o.price
            for t in source_catalog.types for o in t.offerings
        }
        self.pricing = PricingProvider(
            cloud, clock=clock, isolated=settings.isolated_vpc,
            static_prices=static_prices,
            policy=(resilience.policy("pricing") if resilience else None),
            ladder=(resilience.ladder("pricing") if resilience else None))
        self.images = ImageProvider(cloud, clock=clock)
        self.launch_templates = LaunchTemplateProvider(
            cloud, self.images, settings, clock=clock,
            securitygroup_provider=self.security_groups)
        self.instance_types = InstanceTypeProvider(
            source_catalog, self.ice, self.subnets, settings=settings)
        self.instances = InstanceProvider(
            cloud, settings, self.launch_templates, self.subnets, self.ice,
            policy=(resilience.policy("cloud") if resilience else None))
        self.nodetemplates: "dict[str, NodeTemplate]" = {}
        # zone-fold memos (constrain_to_template_zones): strong refs so
        # identity checks can't alias recycled objects
        self._all_zones_memo: "Optional[tuple]" = None
        self._zone_fold_memo: "dict[str, tuple]" = {}
        # authoritative template lookup (the operator wires the kube store
        # here so deletes are honored; the reference gets this for free via
        # the shared kube client, cloudprovider.go:286-300). When unset, the
        # register_nodetemplate registry is the source (standalone use).
        self.template_source = None

    # -- template resolution ---------------------------------------------------

    def register_nodetemplate(self, template: NodeTemplate) -> None:
        template.validate()
        self.nodetemplates[template.name] = template

    def _get_template(self, name: str) -> "Optional[NodeTemplate]":
        if not name:
            return None
        if self.template_source is not None:
            return self.template_source(name)
        return self.nodetemplates.get(name)

    def constrain_to_template_zones(
            self, provisioners: "Sequence[Provisioner]",
            catalog: Catalog) -> "list[Provisioner]":
        """Fold each provisioner's template subnet zones into its zone
        domain, so EVERY solve entry point (provisioning, consolidation
        search, replace revalidation) decides only zones the template can
        launch into. The reference gets this for free by building offerings
        from the template's subnets
        (/root/reference/pkg/cloudprovider/instancetypes.go:86-102); here
        scheduling shares one catalog, so the restriction rides the
        provisioner requirements. Constrained copies are memoized per
        provisioner object + zone set so steady-state callers keep object
        identity (solver caches key on it)."""
        memo = self._all_zones_memo
        if memo is None or memo[0] is not catalog or memo[1] != catalog.seqnum:
            memo = (catalog, catalog.seqnum,
                    {o.zone for t in catalog.types for o in t.offerings})
            self._all_zones_memo = memo
        all_zones = memo[2]
        # prune memo entries for provisioners that no longer exist, so
        # deleted provisioners don't pin their objects forever
        live = {p.name for p in provisioners}
        for stale in [n for n in self._zone_fold_memo if n not in live]:
            del self._zone_fold_memo[stale]
        return [self._zone_constrained(p, all_zones) for p in provisioners]

    def _zone_constrained(self, prov: Provisioner,
                          all_zones: "set[str]") -> Provisioner:
        if not prov.provider_ref:
            return prov
        try:
            template = self._get_template(prov.provider_ref)
        except Exception:
            return prov
        if template is None or not template.subnet_selector:
            return prov
        zones = tuple(sorted(self.subnets.zones(template.subnet_selector)))
        if not zones or set(zones) >= all_zones:
            return prov  # unrestricted (or unresolvable: launch surfaces it)
        memo = self._zone_fold_memo.get(prov.name)
        if memo is not None and memo[0] is prov and memo[1] == zones:
            return memo[2]
        import dataclasses

        from .models.requirements import OP_IN

        constrained = dataclasses.replace(
            prov, requirements=prov.requirements.union(
                Requirements.of((wk.LABEL_ZONE, OP_IN, list(zones)))))
        self._zone_fold_memo[prov.name] = (prov, zones, constrained)
        return constrained

    def resolve_nodetemplate(self, provisioner_or_machine) -> NodeTemplate:
        """providerRef -> NodeTemplate (cloudprovider.go:113-118, 286-300)."""
        ref = getattr(provisioner_or_machine, "provider_ref", None) or getattr(
            getattr(provisioner_or_machine, "spec", None), "machine_template_ref", "")
        if not ref:
            raise cloud_errors.CloudError("NodeTemplateNotFound",
                                          "no nodeTemplate reference")
        template = self._get_template(ref)
        if template is None:
            raise cloud_errors.CloudError("NodeTemplateNotFound", ref)
        return template

    # -- interface methods -----------------------------------------------------

    def get_instance_types(self, provisioner: Optional[Provisioner]) -> "list[InstanceType]":
        """GetInstanceTypes (cloudprovider.go:171-186)."""
        template = None
        if provisioner is not None and provisioner.provider_ref:
            template = self._get_template(provisioner.provider_ref)
        return self.instance_types.list(template).types

    def catalog_for(self, provisioner: Optional[Provisioner] = None) -> Catalog:
        template = None
        if provisioner is not None and provisioner.provider_ref:
            template = self._get_template(provisioner.provider_ref)
        return self.instance_types.list(template)

    def create(self, machine: Machine) -> Machine:
        """Create (cloudprovider.go:112-136): resolve template + compatible
        types, launch, translate instance -> machine status."""
        template = self.resolve_nodetemplate(machine)
        types = self.resolve_instance_types(machine)
        if not types:
            raise cloud_errors.CloudError(
                "UnfulfillableCapacity",
                "all requested instance types were unavailable during launch")
        instance = self.instances.create(template, machine, types)
        return self._instance_to_machine(machine, instance, types)

    def resolve_instance_types(self, machine: Machine) -> "list[InstanceType]":
        """reqs.Compatible ∧ offerings.Available ∧ resources.Fits filter
        (cloudprovider.go:302-321)."""
        catalog = self.instance_types.list(
            self._get_template(machine.spec.machine_template_ref))
        reqs = machine.spec.requirements
        vec = wk.resource_vector(machine.spec.resource_requests)
        out = []
        for t in catalog.filter_compatible(reqs):
            alloc = t.allocatable_vector()
            if all(v <= a for v, a in zip(vec, alloc)):
                out.append(t)
        return out

    def get(self, provider_id: str) -> Machine:
        """Get (cloudprovider.go:139-160)."""
        _, instance_id = parse_provider_id(provider_id)
        instance = self.instances.get_by_id(instance_id)
        return self._bare_instance_machine(instance)

    def list_machines(self) -> "list[Machine]":
        return [self._bare_instance_machine(i)
                for i in self.instances.list_cluster_instances()]

    def delete(self, machine: Machine) -> None:
        """Delete (cloudprovider.go:189-197)."""
        if not machine.status.provider_id:
            return
        _, instance_id = parse_provider_id(machine.status.provider_id)
        self.instances.delete(instance_id)

    def is_machine_drifted(self, machine: Machine) -> bool:
        """Drift = machine's image no longer in the template's resolved set
        (cloudprovider.go:199-217, 255-284)."""
        if not self.settings.feature_gates.drift_enabled:
            return False
        try:
            template = self.resolve_nodetemplate(machine)
        except cloud_errors.CloudError:
            return False
        if not machine.status.image_id:
            return False
        images = self.images.get(template, archs=("amd64", "arm64"))
        return machine.status.image_id not in {i.image_id for i in images}

    def hydrate(self, instance: CloudInstance, kubelet=None) -> Machine:
        """Machine backfill from a pre-existing instance
        (cloudprovider.go:221-251 Hydrate). `kubelet` is the owning
        provisioner's config so the rebuilt Machine reports the same
        kubelet-adjusted allocatable it launched with."""
        m = self._bare_instance_machine(instance, kubelet=kubelet)
        if "karpenter.sh/managed-by" not in instance.tags:
            self.cloud.create_tags(instance.id, {
                "karpenter.sh/managed-by": self.settings.cluster_name})
        return m

    def livez(self) -> bool:
        """LivenessProbe chain (cloudprovider.go:163-168)."""
        return self.instance_types.livez() and self.pricing.livez()

    def name(self) -> str:
        return "tpu"

    # -- translation -----------------------------------------------------------

    def _instance_to_machine(self, machine: Machine, instance: CloudInstance,
                             types: "list[InstanceType]") -> Machine:
        """instanceToMachine (cloudprovider.go:324-365)."""
        itype = next((t for t in types if t.name == instance.instance_type), None)
        labels = dict(machine.labels)
        if itype is not None:
            labels.update(itype.labels_dict())
        labels[wk.LABEL_ZONE] = instance.zone
        labels[wk.LABEL_CAPACITY_TYPE] = instance.capacity_type
        if machine.spec.provisioner_name:
            labels[wk.LABEL_PROVISIONER] = machine.spec.provisioner_name
        machine.labels = labels
        price = self.pricing.spot_price(instance.instance_type, instance.zone) \
            if instance.capacity_type == wk.CAPACITY_TYPE_SPOT \
            else self.pricing.on_demand_price(instance.instance_type, instance.zone)
        alloc = itype.allocatable_vector() if itype else []
        if itype is not None and machine.spec.kubelet is not None:
            # kubelet config shapes the node's reported allocatable exactly
            # as it shaped the scheduling decision (oracle kubelet_* helpers)
            from .oracle.scheduler import (kubelet_overhead_vector,
                                           kubelet_pods_cap)

            kovh = kubelet_overhead_vector(machine.spec.kubelet)
            alloc = [max(0, a - k) for a, k in zip(alloc, kovh)]
            cap = kubelet_pods_cap(machine.spec.kubelet, itype)
            if cap is not None:
                pi = wk.RESOURCE_INDEX[wk.RESOURCE_PODS]
                alloc[pi] = min(alloc[pi], cap)
        # nodeNameConvention (settings.go:29-47; instanceToMachine
        # cloudprovider.go:344-348): the name the node registers with —
        # resource-name = the instance id, ip-name (default) = the
        # lowercased private DNS name (falling back to the instance id for
        # backends that don't surface one)
        if self.settings.node_name_convention == "resource-name":
            node_name = instance.id
        else:
            node_name = (getattr(instance, "private_dns", "") or instance.id).lower()
        machine.status = MachineStatus(
            provider_id=make_provider_id(instance.zone, instance.id),
            instance_type=instance.instance_type,
            zone=instance.zone,
            capacity_type=instance.capacity_type,
            image_id=instance.image_id,
            capacity=dict(itype.capacity) if itype else {},
            allocatable=wk.raw_resources_from_vector(alloc) if itype else {},
            state=LAUNCHED,
            node_name=node_name,
            price=price or 0.0,
        )
        return machine

    def _bare_instance_machine(self, instance: CloudInstance, kubelet=None) -> Machine:
        from .models.machine import MachineSpec

        m = Machine(
            name=instance.tags.get("karpenter.sh/machine", instance.id),
            spec=MachineSpec(
                provisioner_name=instance.tags.get("karpenter.sh/provisioner-name", ""),
                kubelet=kubelet,
            ),
        )
        types = {t.name: t for t in self.instance_types.list().types}
        return self._instance_to_machine(
            m, instance, [types[instance.instance_type]]
            if instance.instance_type in types else [])

    def stop(self):
        self.instances.stop()
