"""Cloud error taxonomy.

Parity target: /root/reference/pkg/errors/errors.go — notFound code set
(:29-37), unfulfillable-capacity (ICE) code set (:38-46:
InsufficientInstanceCapacity, MaxSpotInstanceCountExceeded, VcpuLimitExceeded,
UnfulfillableCapacity, Unsupported), IsNotFound:52, IsUnfulfillableCapacity:66,
IsLaunchTemplateNotFound:70.
"""

from __future__ import annotations

from typing import Optional

NOT_FOUND_CODES = frozenset({
    "InstanceNotFound", "InvalidInstanceID.NotFound", "QueueDoesNotExist",
    "NodeTemplateNotFound", "ResourceNotFound",
})

UNFULFILLABLE_CAPACITY_CODES = frozenset({
    "InsufficientInstanceCapacity", "MaxSpotInstanceCountExceeded",
    "VcpuLimitExceeded", "UnfulfillableCapacity", "Unsupported",
    "InsufficientAcceleratorCapacity",
})

LAUNCH_TEMPLATE_NOT_FOUND = "InvalidLaunchTemplateName.NotFoundException"


class CloudError(Exception):
    def __init__(self, code: str, message: str = ""):
        super().__init__(f"{code}: {message}" if message else code)
        self.code = code
        self.message = message


class FleetError(CloudError):
    """CreateFleet per-pool failure: carries the (instanceType, zone) pools
    that failed so the ICE cache can poison them (instance.go:419-425)."""

    def __init__(self, code: str, failed_pools: "list[tuple[str, str]]", message: str = ""):
        super().__init__(code, message)
        self.failed_pools = failed_pools


def code_of(err: Exception) -> Optional[str]:
    return getattr(err, "code", None)


def is_not_found(err: Exception) -> bool:
    return code_of(err) in NOT_FOUND_CODES


def is_unfulfillable_capacity(err: Exception) -> bool:
    return code_of(err) in UNFULFILLABLE_CAPACITY_CODES


def is_launch_template_not_found(err: Exception) -> bool:
    return code_of(err) == LAUNCH_TEMPLATE_NOT_FOUND


def ignore_not_found(err: Optional[Exception]) -> Optional[Exception]:
    if err is not None and is_not_found(err):
        return None
    return err
