"""Bounded in-process log ring served at /logz (`logs` CLI).

The reference's test tooling fetches controller logs for a run without
shelling into the pod (/root/reference/test/cmd/logs/main.go pulls them
from the log archive by test id). The hermetic analogue keeps the last N
records in memory and serves them over the health listener — `python -m
karpenter_tpu logs` is then kubectl-logs-shaped triage against a live
controller.

Records are kept structured (timestamp, level, logger, formatted line) so
the serving plane can filter by `?level=` and the flight recorder can
embed them as JSON without re-parsing formatted text.
"""

from __future__ import annotations

import collections
import logging
import threading

_LOCK = threading.Lock()
_HANDLER: "RingHandler | None" = None


class RingHandler(logging.Handler):
    """Keep the last `capacity` records, thread-safe."""

    def __init__(self, capacity: int = 2000):
        super().__init__()
        self.ring: "collections.deque[dict]" = collections.deque(maxlen=capacity)
        self.setFormatter(logging.Formatter(
            "%(asctime)s %(levelname)s %(name)s %(message)s"))

    def emit(self, record: logging.LogRecord) -> None:
        try:
            line = self.format(record)
        except Exception:
            return
        entry = {
            "ts": record.created,
            "level": record.levelname,
            "levelno": record.levelno,
            "logger": record.name,
            "line": line,
        }
        with _LOCK:
            self.ring.append(entry)

    def dump_records(self, n: "int | None" = None,
                     level: "str | int | None" = None) -> "list[dict]":
        """Recent structured records, oldest first; `level` keeps records
        at or above that severity (name like "WARNING" or a levelno)."""
        with _LOCK:
            records = list(self.ring)
        if level is not None:
            threshold = _levelno(level)
            records = [r for r in records if r["levelno"] >= threshold]
        return records if n is None else records[-n:]

    def dump(self, n: "int | None" = None,
             level: "str | int | None" = None) -> "list[str]":
        return [r["line"] for r in self.dump_records(n, level)]


def _levelno(level: "str | int") -> int:
    if isinstance(level, int):
        return level
    no = logging.getLevelName(str(level).strip().upper())
    if not isinstance(no, int):  # getLevelName echoes "Level FOO" strings
        raise ValueError(f"unknown log level: {level!r}")
    return no


def install(capacity: int = 2000) -> RingHandler:
    """Attach the process-wide ring to the package logger tree (idempotent)."""
    global _HANDLER
    with _LOCK:
        if _HANDLER is not None:
            return _HANDLER
        _HANDLER = RingHandler(capacity)
    pkg = logging.getLogger("karpenter")
    pkg.addHandler(_HANDLER)
    if pkg.level == logging.NOTSET:
        # without an explicit level the tree inherits root (WARNING unless
        # basicConfig ran), and INFO records never reach the ring
        pkg.setLevel(logging.INFO)
    return _HANDLER


def dump(n: "int | None" = None,
         level: "str | int | None" = None) -> "list[str]":
    """Recent formatted lines, oldest first (empty when no ring installed)."""
    h = _HANDLER
    return h.dump(n, level) if h is not None else []


def dump_records(n: "int | None" = None,
                 level: "str | int | None" = None) -> "list[dict]":
    """Recent structured records for bundle inclusion (JSON-lines shaped)."""
    h = _HANDLER
    return h.dump_records(n, level) if h is not None else []
