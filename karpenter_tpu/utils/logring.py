"""Bounded in-process log ring served at /logz (`logs` CLI).

The reference's test tooling fetches controller logs for a run without
shelling into the pod (/root/reference/test/cmd/logs/main.go pulls them
from the log archive by test id). The hermetic analogue keeps the last N
records in memory and serves them over the health listener — `python -m
karpenter_tpu logs` is then kubectl-logs-shaped triage against a live
controller.
"""

from __future__ import annotations

import collections
import logging
import threading

_LOCK = threading.Lock()
_HANDLER: "RingHandler | None" = None


class RingHandler(logging.Handler):
    """Keep the last `capacity` formatted records, thread-safe."""

    def __init__(self, capacity: int = 2000):
        super().__init__()
        self.ring: "collections.deque[str]" = collections.deque(maxlen=capacity)
        self.setFormatter(logging.Formatter(
            "%(asctime)s %(levelname)s %(name)s %(message)s"))

    def emit(self, record: logging.LogRecord) -> None:
        try:
            line = self.format(record)
        except Exception:
            return
        with _LOCK:
            self.ring.append(line)

    def dump(self, n: "int | None" = None) -> "list[str]":
        with _LOCK:
            lines = list(self.ring)
        return lines if n is None else lines[-n:]


def install(capacity: int = 2000) -> RingHandler:
    """Attach the process-wide ring to the package logger tree (idempotent)."""
    global _HANDLER
    with _LOCK:
        if _HANDLER is not None:
            return _HANDLER
        _HANDLER = RingHandler(capacity)
    pkg = logging.getLogger("karpenter")
    pkg.addHandler(_HANDLER)
    if pkg.level == logging.NOTSET:
        # without an explicit level the tree inherits root (WARNING unless
        # basicConfig ran), and INFO records never reach the ring
        pkg.setLevel(logging.INFO)
    return _HANDLER


def dump(n: "int | None" = None) -> "list[str]":
    """Recent records, oldest first (empty when no ring is installed)."""
    h = _HANDLER
    return h.dump(n) if h is not None else []
