"""Recorded on-chip capture lookups (routing + bench reporting).

hack/tpu_capture.py records benchmark captures into benchmarks/results/;
this module is the read side shared by bench.py (report the freshest chip
evidence) and the provisioning controller (data-driven device-vs-native
routing threshold). Kept inside the package so the controller does not
import repo-root script modules.

Reference analogue: the reference sizes its behavior from measured constants
(batching windows, cache TTLs — pkg/batcher/createfleet.go:33-36); here the
measured constant is the solve-latency crossover between the host C++ scan
and the device kernel.
"""

from __future__ import annotations

import json
import os
from typing import Optional

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
RESULTS_DIR = os.path.join(_REPO_ROOT, "benchmarks", "results")


def _iter_captures(results_dir: Optional[str] = None):
    """Recorded captures, newest first, skipping degraded/unreadable ones."""
    d = results_dir or RESULTS_DIR
    try:
        names = sorted(n for n in os.listdir(d)
                       if n.startswith("tpu_") and n.endswith(".json"))
    except FileNotFoundError:
        return
    for name in reversed(names):
        try:
            with open(os.path.join(d, name)) as f:
                rec = json.load(f)
        except (OSError, ValueError):
            continue
        if rec.get("degraded"):
            continue
        yield rec


def latest_capture(results_dir: Optional[str] = None) -> "Optional[dict]":
    """Most recent non-degraded recorded capture, or None. May be a partial
    record (rec["partial"] set) when a relay wedge cut a capture short —
    callers that need a specific section should fall back through
    _iter_captures."""
    return next(_iter_captures(results_dir), None)


def route_crossover(default: "Optional[int]" = None) -> "Optional[int]":
    """Pod-count threshold below which the in-process native scan beats the
    device kernel. Resolution order:

      1. KARPENTER_TPU_ROUTE_CROSSOVER env (operator override; "inf" or
         "none" disables the device path preference entirely),
      2. the freshest recorded capture's measured crossover_pods
         (null there = the device never won the sweep -> None),
      3. `default`.

    Returns None when no threshold is known — callers treat None as "prefer
    the native path at every size the sweep covered" (measured reality on a
    tunneled chip, where the ~66 ms RTT dominates every solve; see
    docs/designs/solver-boundary.md routing table).
    """
    env = os.environ.get("KARPENTER_TPU_ROUTE_CROSSOVER", "").strip().lower()
    if env:
        if env in ("inf", "none", "native"):
            return None
        try:
            return int(env)
        except ValueError:
            pass
    # newest capture that actually measured the crossover (a salvaged
    # partial may have wedged before the sweep finished)
    for cap in _iter_captures():
        if "crossover_pods" in cap:
            return cap["crossover_pods"]  # may legitimately be None
    return default
