"""Single home for the "pin the jax platform before any backend touch" dance.

The deployment env's sitecustomize registers a tunneled TPU ("axon") PJRT
backend in every interpreter and sets JAX_PLATFORMS=axon, so a bare
``jax.devices()`` can hang indefinitely when the loopback relay wedges.
Three surfaces need the same defense (tests/conftest.py, __graft_entry__.py,
bench.py); this module is the one copy they share so fallback semantics
can't drift.

Reference analogue: none — this is deployment-env hardening, the moral
equivalent of the reference's operator env bootstrapping
(cmd/controller/main.go:33-65 reading env/flags before client init).
"""

import os
import subprocess
import sys
import time


def pin(platform: str, n_devices=None):
    """Pin the jax platform BEFORE any backend touch. Safe to call when
    backends are already initialized (the config updates are then no-ops and
    the caller relies on the driver's own env pin). Returns (jax, warning) —
    warning is None or the swallowed-config-error text."""
    os.environ["JAX_PLATFORMS"] = platform
    if n_devices is not None:
        flags = os.environ.get("XLA_FLAGS", "")
        if "xla_force_host_platform_device_count" not in flags:
            os.environ["XLA_FLAGS"] = (
                flags + f" --xla_force_host_platform_device_count={n_devices}").strip()
    import jax

    warning = None
    updates = [("jax_platforms", platform)]
    if n_devices is not None:
        updates.append(("jax_num_cpu_devices", n_devices))
    for key, val in updates:
        try:
            jax.config.update(key, val)
        except (RuntimeError, ValueError, AttributeError) as e:
            # RuntimeError/ValueError: backends already initialized; the env
            # pin must suffice. AttributeError: this jax predates the option
            # (jax_num_cpu_devices) — XLA_FLAGS above covers the device count.
            warning = str(e)[:160]
    try:
        # persistent compile cache: the stress-shape programs (50k-pod
        # dryrun, consolidation grids) cost 10-60s each to compile on the
        # virtual-CPU mesh; caching makes repeat runs (tests, the driver's
        # verify-entry, bench re-runs) pay it once per machine.
        import tempfile
        default_cache = os.path.join(
            tempfile.gettempdir(),
            f"karpenter_tpu_jax_cache_{os.getuid()}")  # per-user: a shared
        # predictable /tmp path is both unwritable for the second user and a
        # cache-poisoning surface (compiled XLA binaries deserialize+run)
        jax.config.update("jax_compilation_cache_dir",
                          os.environ.get("KARPENTER_TPU_JAX_CACHE",
                                         default_cache))
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 2.0)
    except Exception:
        pass  # older jax without the knob: compiles stay in-memory only
    return jax, warning


def pin_cpu(n_devices: int = 8):
    """Force the CPU platform with >= n_devices virtual devices. Returns jax."""
    return pin("cpu", n_devices)[0]


def probe_tpu(attempts: int = 3, timeout_s: int = 60, backoff_s: int = 10):
    """Init the axon backend in a throwaway subprocess with a hard timeout
    (a PJRT-init hang — even at interpreter startup — only costs the probe).
    Returns (ok, note); note carries the per-attempt failure trail."""
    env = dict(os.environ, JAX_PLATFORMS="axon")
    # the config.update is guarded like pin(): sitecustomize may have already
    # initialized the backend at interpreter startup, and a healthy TPU must
    # not be reported down just because the late pin raises
    code = ("import jax\n"
            "try: jax.config.update('jax_platforms','axon')\n"
            "except (RuntimeError, ValueError): pass\n"
            "d = jax.devices(); print('PROBE_OK', d[0].platform, len(d))")
    notes = []
    for attempt in range(1, attempts + 1):
        try:
            r = subprocess.run([sys.executable, "-c", code], env=env,
                               capture_output=True, text=True, timeout=timeout_s)
            if r.returncode == 0 and "PROBE_OK" in r.stdout:
                return True, f"probe ok on attempt {attempt}"
            notes.append(f"attempt {attempt}: rc={r.returncode} "
                         f"{(r.stderr or r.stdout).strip()[-160:]}")
        except subprocess.TimeoutExpired:
            notes.append(f"attempt {attempt}: timeout {timeout_s}s")
        if attempt < attempts:
            time.sleep(backoff_s * attempt)
    return False, "; ".join(notes)
