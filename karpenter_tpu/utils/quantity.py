"""Kubernetes resource-quantity parsing.

Parity target: the reference consumes k8s `resource.Quantity` values everywhere a
pod requests resources or an instance type advertises capacity (e.g.
/root/reference/pkg/cloudprovider/instancetype.go:128-163 builds capacity from
vCPU counts / MiB memory; examples/workloads/inflate.yaml uses "1" cpu / "256M").

We normalize every quantity to an integer in a canonical per-resource unit so
that downstream array math (float32 on TPU) stays exact: values are kept under
2**24 whenever realistic, and the scalar oracle uses exact ints.
"""

from __future__ import annotations

import re
from fractions import Fraction

_SUFFIX = {
    "": 1,
    "k": 10**3,
    "M": 10**6,
    "G": 10**9,
    "T": 10**12,
    "P": 10**15,
    "E": 10**18,
    "Ki": 2**10,
    "Mi": 2**20,
    "Gi": 2**30,
    "Ti": 2**40,
    "Pi": 2**50,
    "Ei": 2**60,
}

_QTY_RE = re.compile(r"^\s*([+-]?[0-9]*\.?[0-9]+(?:[eE][+-]?[0-9]+)?)\s*([A-Za-z]*)\s*$")


def parse_quantity(value: "str | int | float") -> Fraction:
    """Parse a k8s quantity string to an exact Fraction of base units.

    "100m" -> 1/10, "256M" -> 256_000_000, "1Gi" -> 2**30, "2" -> 2.
    """
    if isinstance(value, bool):
        raise ValueError(f"invalid quantity: {value!r}")
    if isinstance(value, int):
        return Fraction(value)
    if isinstance(value, float):
        return Fraction(value).limit_denominator(10**9)
    m = _QTY_RE.match(value)
    if not m:
        raise ValueError(f"invalid quantity: {value!r}")
    number, suffix = m.groups()
    if suffix == "m":  # milli
        return Fraction(number) / 1000
    if suffix not in _SUFFIX:
        raise ValueError(f"invalid quantity suffix: {value!r}")
    return Fraction(number) * _SUFFIX[suffix]


def cpu_millis(value: "str | int | float") -> int:
    """CPU quantity -> integer millicores ("1" -> 1000, "100m" -> 100)."""
    return int(parse_quantity(value) * 1000)


def mem_bytes(value: "str | int | float") -> int:
    """Memory/storage quantity -> integer bytes."""
    return int(parse_quantity(value))


def count(value: "str | int | float") -> int:
    """Counted resource (pods, GPUs, ENIs) -> integer."""
    return int(parse_quantity(value))


def format_cpu(millis: int) -> str:
    if millis % 1000 == 0:
        return str(millis // 1000)
    return f"{millis}m"


def format_mem(nbytes: int) -> str:
    for suffix, mult in (("Gi", 2**30), ("Mi", 2**20), ("Ki", 2**10)):
        if nbytes % mult == 0:
            return f"{nbytes // mult}{suffix}"
    return str(nbytes)
