"""Injectable clock (reference analogue: k8s clock + clock/testing fakeClock,
used for TTL/cache time travel at pkg/cloudprovider/suite_test.go:94)."""

from __future__ import annotations

import threading
import time


class Clock:
    def now(self) -> float:
        return time.monotonic()

    def sleep(self, seconds: float) -> None:
        time.sleep(seconds)


class WallClock(Clock):
    """Epoch-time clock for cross-process evidence. `Clock` is monotonic,
    which is per-process — timestamps that must be COMPARED across
    processes (the federated scrape plane's staleness_s: view clock minus
    a subprocess replica's self-reported statusz ts) need a shared clock
    domain, and wall time is the only one two pids have."""

    def now(self) -> float:
        return time.time()


class FakeClock(Clock):
    """Manually stepped clock; wakes sleepers when stepped past their deadline."""

    def __init__(self, start: float = 0.0):
        self._now = start
        self._cond = threading.Condition()

    def now(self) -> float:
        with self._cond:
            return self._now

    def sleep(self, seconds: float) -> None:
        deadline = self.now() + seconds
        with self._cond:
            while self._now < deadline:
                self._cond.wait(timeout=0.05)

    def step(self, seconds: float) -> None:
        with self._cond:
            self._now += seconds
            self._cond.notify_all()
