"""Launch-template provider.

Parity target: /root/reference/pkg/cloudprovider/launchtemplate.go — one cloud
LT per resolved (image x userdata x options) hash, name
`Karpenter-<cluster>-<hash>` (:128-134), ensure = cache -> describe -> create
(:162-235), cache eviction deletes the cloud LT (:289-303), leader-gated
hydration from the cluster tag (:270-287), static LT passthrough (:93-96),
Invalidate on LT-not-found (:118).
"""

from __future__ import annotations

import hashlib
import json
import logging
import threading
from typing import Optional, Sequence

from ..apis.nodetemplate import NodeTemplate
from ..apis.settings import Settings
from ..fake.cloud import LaunchTemplate
from ..utils import errors as cloud_errors
from ..models.pod import Taint
from ..utils.clock import Clock
from .images import BootstrapConfig, ImageProvider, get_family

log = logging.getLogger("karpenter.launchtemplate")

CLUSTER_TAG_KEY = "karpenter.k8s.tpu/cluster"


class LaunchTemplateProvider:
    def __init__(self, cloud, image_provider: ImageProvider, settings: Settings,
                 clock: Optional[Clock] = None, securitygroup_provider=None):
        self.cloud = cloud
        self.images = image_provider
        self.security_groups = securitygroup_provider
        self.settings = settings
        self._known: "dict[str, str]" = {}  # hash-name -> name (presence cache)
        self._lock = threading.Lock()
        self._hydrated = False

    def _name(self, spec_hash: str) -> str:
        return f"Karpenter-{self.settings.cluster_name}-{spec_hash}"

    def ensure_all(
        self,
        template: NodeTemplate,
        labels: "dict[str, str]",
        taints: "Sequence[Taint]" = (),
        archs: Sequence[str] = ("amd64",),
        kubelet=None,  # apis.provisioner.KubeletConfiguration
    ) -> "dict[str, list[str]]":
        """Resolve per-arch launch templates; returns {lt_name: [archs]}.

        Static passthrough: a user-managed LT name skips resolution
        (launchtemplate.go:93-96)."""
        if template.launch_template_name:
            return {template.launch_template_name: list(archs)}
        # Constrained security groups resolve into the LT; an empty match is a
        # launch failure, not a silently ungrouped node
        # (launchtemplate.go:141-154 "no security groups exist given
        # constraints", SecurityGroupIds:210). A selector with no provider to
        # resolve it is a wiring bug and fails just as loudly.
        sg_ids: "list[str]" = []
        if self.security_groups is not None:
            sg_ids = self.security_groups.ids(template.security_group_selector)
        if not sg_ids and template.security_group_selector:
            raise cloud_errors.CloudError(
                "InvalidParameterValue",
                "no security groups exist given constraints")
        out: "dict[str, list[str]]" = {}
        family = get_family(template.image_family)
        for image in self.images.get(template, archs):
            cfg = BootstrapConfig(
                cluster_name=self.settings.cluster_name,
                cluster_endpoint=self.settings.cluster_endpoint,
                labels=labels,
                taints=tuple(taints),
                kubelet=kubelet,
                custom_userdata=template.userdata,
            )
            userdata = family.userdata(cfg)
            spec = {
                "image": image.image_id,
                "userdata": userdata,
                "metadata": dataclass_dict(template.metadata_options),
                "bdm": [dataclass_dict(b) for b in template.block_device_mappings],
                "monitoring": template.detailed_monitoring,
                "profile": template.instance_profile or self.settings.default_instance_profile,
                # tags are carried on the created LT, so they must be hashed:
                # templates differing only in tags may not share an LT
                "tags": dict(sorted(template.tags.items())),
                "sgs": sorted(sg_ids),
            }
            spec_hash = hashlib.sha256(
                json.dumps(spec, sort_keys=True).encode()).hexdigest()[:16]
            name = self._ensure(spec_hash, spec, template)
            out.setdefault(name, []).append(image.arch)
        return out

    def _ensure(self, spec_hash: str, spec: dict, template: NodeTemplate) -> str:
        """`spec` is the same resolved dict the hash was computed from — the
        created LT must carry exactly what was hashed."""
        name = self._name(spec_hash)
        with self._lock:
            if name in self._known:
                return name
        existing = {lt.name for lt in self.cloud.describe_launch_templates(
            CLUSTER_TAG_KEY, self.settings.cluster_name)}
        if name not in existing:
            self.cloud.create_launch_template(LaunchTemplate(
                name=name, image_id=spec["image"], userdata=spec["userdata"],
                tags={CLUSTER_TAG_KEY: self.settings.cluster_name, **template.tags},
                metadata_options=spec["metadata"],
                block_devices=spec["bdm"],
                monitoring=spec["monitoring"],
                instance_profile=spec["profile"],
                security_group_ids=spec["sgs"],
            ))
            log.info("created launch template %s", name)
        with self._lock:
            self._known[name] = name
        return name

    def invalidate(self, name: str) -> None:
        """Drop from cache after LT-not-found (launchtemplate.go:118)."""
        with self._lock:
            self._known.pop(name, None)

    def hydrate(self) -> int:
        """Leader-elected warm-up: pre-populate the cache from cluster-tagged
        LTs (launchtemplate.go:270-287)."""
        found = self.cloud.describe_launch_templates(
            CLUSTER_TAG_KEY, self.settings.cluster_name)
        with self._lock:
            for lt in found:
                self._known[lt.name] = lt.name
            self._hydrated = True
        return len(found)

    def delete_all(self) -> int:
        """GC every cluster-owned LT (nodetemplate finalizer path)."""
        count = 0
        for lt in self.cloud.describe_launch_templates(
                CLUSTER_TAG_KEY, self.settings.cluster_name):
            try:
                self.cloud.delete_launch_template(lt.name)
                count += 1
            except Exception:
                pass
            self.invalidate(lt.name)
        return count


def dataclass_dict(obj) -> dict:
    import dataclasses

    return dataclasses.asdict(obj)
