"""Pricing provider + pricing-source client boundary.

Parity target: /root/reference/pkg/cloudprovider/pricing.go — on-demand +
per-zone spot prices (:175-187 OnDemandPrice/SpotPrice), 12h background
refresh (:83, 139-147), embedded static fallback prices served until the
first successful update (:100-116), isolated-VPC mode disabling updates
(:119-121), liveness check that the refresh loop isn't wedged (:437-443).

The client boundary is `PricingSource` (get_prices): `fake.cloud.FakeCloud`
is the hermetic impl; `RestPricingSource` is the real-client stub — paged
JSON endpoints for on-demand and per-zone spot, with the reference's
INDEPENDENT update semantics (pricing.go:202-243: an OD fetch that succeeds
applies even when the spot fetch fails, and vice versa).
"""

from __future__ import annotations

import json
import logging
import threading
import urllib.error
import urllib.request
from typing import Optional, Protocol, runtime_checkable

from ..cache import PRICING_REFRESH_PERIOD
from ..metrics import NAMESPACE, REGISTRY
from ..utils.clock import Clock

log = logging.getLogger("karpenter.pricing")


@runtime_checkable
class PricingSource(Protocol):
    """What the provider needs from a price feed: the full
    (instance type, capacity type, zone) -> $/h map for one refresh."""

    def get_prices(self) -> "dict[tuple[str, str, str], float]": ...


class RestPricingSource:
    """PricingSource over paged JSON endpoints (the Pricing-API +
    DescribeSpotPriceHistory analogue, pricing.go:283-316, 379-435).

    GET {base}/on-demand?page=N -> {"prices": [{"instanceType", "price"}...],
                                    "next": true|false}
    GET {base}/spot?page=N      -> {"prices": [{"instanceType", "zone",
                                    "price"}...], "next": true|false}

    On-demand prices fan out across `zones`; the two feeds update
    independently — a partial outage degrades, never blanks, the map.
    """

    def __init__(self, base_url: str, zones: "list[str]",
                 timeout: float = 10.0, max_pages: int = 100, policy=None):
        self.base_url = base_url.rstrip("/")
        self.zones = list(zones)
        self.timeout = timeout
        self.max_pages = max_pages
        # resilience.RetryPolicy for the pricing edge; when set, every PAGE
        # fetch is individually retried, so one transient 5xx mid-pagination
        # no longer aborts the whole refresh ("partial outage degrades,
        # never blanks" must hold WITHIN a refresh, not just across them)
        self.policy = policy

    def _fetch_page(self, path: str, page: int) -> dict:
        with urllib.request.urlopen(
                f"{self.base_url}/{path}?page={page}",
                timeout=self.timeout) as resp:
            return json.loads(resp.read())

    @staticmethod
    def _transient(e: BaseException) -> bool:
        if isinstance(e, urllib.error.HTTPError):
            return e.code >= 500
        return isinstance(e, (urllib.error.URLError, TimeoutError, OSError))

    def _fetch_pages(self, path: str) -> "list[dict]":
        out: "list[dict]" = []
        for page in range(self.max_pages):
            if self.policy is not None:
                doc = self.policy.call(
                    lambda path=path, page=page: self._fetch_page(path, page),
                    retriable=self._transient)
            else:
                doc = self._fetch_page(path, page)
            out.extend(doc.get("prices", []))
            if not doc.get("next"):
                break
        return out

    def get_prices(self) -> "dict[tuple[str, str, str], float]":
        prices: "dict[tuple[str, str, str], float]" = {}
        errors = []
        try:
            for row in self._fetch_pages("on-demand"):
                for z in self.zones:
                    prices[(row["instanceType"], "on-demand", z)] = \
                        float(row["price"])
        except (urllib.error.URLError, OSError, ValueError, KeyError) as e:
            errors.append(f"on-demand: {e}")
        try:
            for row in self._fetch_pages("spot"):
                prices[(row["instanceType"], "spot", row["zone"])] = \
                    float(row["price"])
        except (urllib.error.URLError, OSError, ValueError, KeyError) as e:
            errors.append(f"spot: {e}")
        if errors:
            # independent updates (pricing.go:202-243): whatever side
            # succeeded still applies; both failing yields {} and the
            # provider keeps its previous/static map
            log.warning("pricing fetch partial failure: %s", "; ".join(errors))
        return prices


class PricingProvider:
    def __init__(self, cloud: PricingSource, clock: Optional[Clock] = None,
                 isolated: bool = False,
                 static_prices: "Optional[dict[tuple[str, str, str], float]]" = None,
                 policy=None, ladder=None, registry=None):
        self.cloud = cloud
        self.clock = clock or Clock()
        self.isolated = isolated
        # how old the price map a consumer would read right now is, split
        # by the fallback rung serving it — the spot forecaster and the
        # storm runbook both key off "static AND stale" (a live rung is
        # allowed to be briefly stale between refresh periods)
        reg = registry if registry is not None else REGISTRY
        self._staleness_gauge = reg.gauge(
            f"{NAMESPACE}_pricing_price_staleness_seconds",
            "Age of the served price map in seconds, by fallback rung "
            "(the static rung ages from provider start).", ("rung",))
        self._created_ts = self.clock.now()
        # live->static promoted to an explicit DegradeLadder: rung 0 = live
        # refreshes, rung 1 = sticky static fallback with recovery probes
        self.ladder = ladder
        # a RestPricingSource built without its own policy inherits ours so
        # page fetches go through the shared pricing-edge budget/breaker
        if (policy is not None and hasattr(cloud, "policy")
                and getattr(cloud, "policy") is None):
            cloud.policy = policy
        self._lock = threading.Lock()
        # static fallback until first refresh (pricing.go:100-116); by default
        # seeded from the generated fleet catalog table
        if static_prices is None:
            from .instancetypes import generate_fleet_catalog

            static_prices = {}
            for t in generate_fleet_catalog().types:
                for o in t.offerings:
                    static_prices[(t.name, o.capacity_type, o.zone)] = o.price
        self._prices: "dict[tuple[str, str, str], float]" = dict(static_prices)
        self._last_update: Optional[float] = None
        self._updates = 0

    def on_demand_price(self, instance_type: str, zone: str = "") -> Optional[float]:
        with self._lock:
            if zone:
                return self._prices.get((instance_type, "on-demand", zone))
            for (it, ct, _z), p in self._prices.items():
                if it == instance_type and ct == "on-demand":
                    return p
            return None

    def spot_price(self, instance_type: str, zone: str) -> Optional[float]:
        with self._lock:
            return self._prices.get((instance_type, "spot", zone))

    def update(self) -> bool:
        """One refresh cycle (updatePricing, pricing.go:202). Returns success.
        With a ladder wired, a degraded provider STAYS on static prices
        between recovery probes instead of re-timing-out against a dead
        endpoint every period."""
        if self.isolated:
            return False
        if self.ladder is not None and self.ladder.start_rung() > 0:
            return False  # sticky static rung; next probe re-attempts live
        try:
            fresh = self.cloud.get_prices()
        except Exception as e:
            log.warning("pricing update failed: %s", e)
            if self.ladder is not None:
                self.ladder.record_failure(0)
            return False
        if not fresh:
            if self.ladder is not None:
                self.ladder.record_failure(0)
            return False
        with self._lock:
            self._prices.update(fresh)
            self._last_update = self.clock.now()
            self._updates += 1
        if self.ladder is not None:
            self.ladder.record_success(0)
        self.observe_staleness()
        return True

    def rung_name(self) -> str:
        """Which fallback rung the served prices come from: the ladder's
        verdict when one is wired, else live-after-first-update."""
        if self.ladder is not None:
            try:
                return self.ladder.rung_name()
            except Exception:
                pass
        return "live" if self._updates else "static"

    def staleness_seconds(self) -> float:
        """Age of the price map a read would serve right now. On the
        static rung (never updated) this ages from provider start — the
        embedded table's numbers are as old as the process."""
        with self._lock:
            last = self._last_update
        base = self._created_ts if last is None else last
        return max(0.0, self.clock.now() - base)

    def observe_staleness(self) -> dict:
        """Refresh the per-rung staleness gauge; returns the statusz
        `pricing` fields."""
        age = self.staleness_seconds()
        rung = self.rung_name()
        self._staleness_gauge.set(round(age, 3), rung=rung)
        with self._lock:
            updates = self._updates
        return {"rung": rung, "staleness_seconds": round(age, 3),
                "updates": updates}

    def livez(self) -> bool:
        """Healthy if updates aren't wedged (pricing.go:437-443): either we
        never started (static prices fine) or the last refresh isn't more
        than 2 periods old."""
        with self._lock:
            if self.isolated or self._last_update is None:
                return True
            return self.clock.now() - self._last_update < 2 * PRICING_REFRESH_PERIOD

    def start_refresh_loop(self, stop_event: threading.Event,
                           period: float = PRICING_REFRESH_PERIOD) -> threading.Thread:
        def loop():
            while not stop_event.is_set():
                self.update()
                stop_event.wait(period)

        t = threading.Thread(target=loop, name="pricing-refresh", daemon=True)
        t.start()
        return t
