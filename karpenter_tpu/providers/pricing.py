"""Pricing provider.

Parity target: /root/reference/pkg/cloudprovider/pricing.go — on-demand +
per-zone spot prices (:175-187 OnDemandPrice/SpotPrice), 12h background
refresh (:83, 139-147), embedded static fallback prices served until the
first successful update (:100-116), isolated-VPC mode disabling updates
(:119-121), liveness check that the refresh loop isn't wedged (:437-443).
"""

from __future__ import annotations

import logging
import threading
from typing import Optional

from ..cache import PRICING_REFRESH_PERIOD
from ..utils.clock import Clock

log = logging.getLogger("karpenter.pricing")


class PricingProvider:
    def __init__(self, cloud, clock: Optional[Clock] = None, isolated: bool = False,
                 static_prices: "Optional[dict[tuple[str, str, str], float]]" = None):
        self.cloud = cloud
        self.clock = clock or Clock()
        self.isolated = isolated
        self._lock = threading.Lock()
        # static fallback until first refresh (pricing.go:100-116); by default
        # seeded from the generated fleet catalog table
        if static_prices is None:
            from .instancetypes import generate_fleet_catalog

            static_prices = {}
            for t in generate_fleet_catalog().types:
                for o in t.offerings:
                    static_prices[(t.name, o.capacity_type, o.zone)] = o.price
        self._prices: "dict[tuple[str, str, str], float]" = dict(static_prices)
        self._last_update: Optional[float] = None
        self._updates = 0

    def on_demand_price(self, instance_type: str, zone: str = "") -> Optional[float]:
        with self._lock:
            if zone:
                return self._prices.get((instance_type, "on-demand", zone))
            for (it, ct, _z), p in self._prices.items():
                if it == instance_type and ct == "on-demand":
                    return p
            return None

    def spot_price(self, instance_type: str, zone: str) -> Optional[float]:
        with self._lock:
            return self._prices.get((instance_type, "spot", zone))

    def update(self) -> bool:
        """One refresh cycle (updatePricing, pricing.go:202). Returns success."""
        if self.isolated:
            return False
        try:
            fresh = self.cloud.get_prices()
        except Exception as e:
            log.warning("pricing update failed: %s", e)
            return False
        if not fresh:
            return False
        with self._lock:
            self._prices.update(fresh)
            self._last_update = self.clock.now()
            self._updates += 1
        return True

    def livez(self) -> bool:
        """Healthy if updates aren't wedged (pricing.go:437-443): either we
        never started (static prices fine) or the last refresh isn't more
        than 2 periods old."""
        with self._lock:
            if self.isolated or self._last_update is None:
                return True
            return self.clock.now() - self._last_update < 2 * PRICING_REFRESH_PERIOD

    def start_refresh_loop(self, stop_event: threading.Event,
                           period: float = PRICING_REFRESH_PERIOD) -> threading.Thread:
        def loop():
            while not stop_event.is_set():
                self.update()
                stop_event.wait(period)

        t = threading.Thread(target=loop, name="pricing-refresh", daemon=True)
        t.start()
        return t
