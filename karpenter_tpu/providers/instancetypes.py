"""Instance-type catalog provider (+ synthetic fleet generator).

Parity target: InstanceTypeProvider (/root/reference/pkg/cloudprovider/
instancetypes.go:51-121 List/createOfferings + seqnum memoization) and the
InstanceType construction pipeline (instancetype.go:50-163: requirements from
shape labels, capacity minus overheads).

Two concrete sources:
- `generate_fleet_catalog`: a synthetic-but-realistic ~600-type fleet (the
  reference's EC2 catalog scale, cloudprovider.go:58-60 + 771-line price
  table) used by benchmarks and the fake cloud backend. Generated from shape
  grammar, NOT copied from AWS data.
- the fake cloud backend (karpenter_tpu/fake) serves per-test fixtures.

Overhead model (re-derived from instancetype.go:229-319 semantics):
- memory: vmMemoryOverheadPercent (default 7.5%) of capacity
- kubeReserved CPU: regressive curve on core count
- kubeReserved memory: 11 MiB per supported pod + 255 MiB
- eviction threshold: 100 MiB
"""

from __future__ import annotations

import dataclasses as _dc
from typing import Optional, Sequence

from ..apis import wellknown as wk
from ..models.instancetype import Catalog, InstanceType, Offering, Offerings
from ..utils.quantity import mem_bytes

VM_MEMORY_OVERHEAD_PERCENT = 0.075  # settings default (settings.go:54-65)


def kube_reserved_cpu_millis(cores: int) -> int:
    """Regressive kubelet CPU reservation curve (instancetype.go:259-278
    semantics: 6% of the first core, 1% of the second, 0.5% of the next two,
    0.25% of the rest)."""
    millis = 0
    remaining = cores * 1000
    tiers = [(1000, 0.06), (1000, 0.01), (2000, 0.005)]
    for width, frac in tiers:
        take = min(remaining, width)
        millis += int(take * frac)
        remaining -= take
    millis += int(remaining * 0.0025)
    return millis


def node_overhead(cpu_millis: int, memory_bytes: int, pods: int,
                  vm_overhead_percent: float = VM_MEMORY_OVERHEAD_PERCENT,
                  ) -> "dict[str, int]":
    kube_mem = (11 * pods + 255) * 2**20
    eviction = 100 * 2**20
    vm_overhead = int(memory_bytes * vm_overhead_percent)
    return {
        wk.RESOURCE_CPU: kube_reserved_cpu_millis(cpu_millis // 1000),
        wk.RESOURCE_MEMORY: vm_overhead + kube_mem + eviction,
    }


# shape grammar: (category, family prefix, generations, mem GiB per cpu, price $/cpu-hr)
_FAMILIES = (
    ("c", "compute", (3, 4, 5, 6, 7, 8), 2, 0.044),
    ("m", "general", (3, 4, 5, 6, 7, 8), 4, 0.050),
    ("r", "memory", (3, 4, 5, 6, 7, 8), 8, 0.062),
    ("t", "burst", (2, 3, 4), 4, 0.041),
    ("c-arm", "compute", (6, 7, 8, 9), 2, 0.037),
    ("m-arm", "general", (6, 7, 8, 9), 4, 0.042),
    ("r-arm", "memory", (7, 8, 9), 8, 0.052),
    ("d", "storage", (2, 3), 4, 0.055),
    ("i", "io", (3, 4), 8, 0.078),
    ("x", "xmem", (1, 2), 16, 0.10),
    ("hpc", "hpc", (6, 7), 2, 0.09),
    ("g", "gpu", (3, 4, 5, 6), 8, 0.35),
    ("inf", "inference", (1, 2), 4, 0.12),
    ("trn", "training", (1, 2), 8, 0.40),
    ("tpu", "accel", (3, 4, 5, 6), 16, 0.30),
)
_SIZES = ((1, "medium"), (2, "large"), (4, "xlarge"), (8, "2xlarge"), (16, "4xlarge"),
          (32, "8xlarge"), (48, "12xlarge"), (64, "16xlarge"), (96, "24xlarge"),
          (128, "32xlarge"), (192, "48xlarge"))


def generate_fleet_catalog(
    zones: Sequence[str] = ("zone-1a", "zone-1b", "zone-1c"),
    spot_discount: float = 0.65,
    max_types: Optional[int] = None,
) -> Catalog:
    """~600-type synthetic fleet across 8 families x 9 sizes x generations."""
    types: "list[InstanceType]" = []
    for fam, category, gens, mem_per_cpu, price_per_cpu in _FAMILIES:
        arch = "arm64" if fam.endswith("-arm") else "amd64"
        for gen in gens:
            for cpu, size in _SIZES:
                if fam == "t" and cpu > 8:
                    continue
                name = f"{fam}{gen}.{size}"
                mem_gib = cpu * mem_per_cpu
                pods = min(110, max(8, cpu * 8))
                cpu_m = cpu * 1000
                mem_b = mem_gib * 2**30
                extended: "dict[str, int]" = {}
                extra = {
                    wk.LABEL_INSTANCE_CATEGORY: category,
                    wk.LABEL_INSTANCE_GENERATION: str(gen),
                }
                if fam == "g" and cpu >= 8:
                    extended[wk.RESOURCE_NVIDIA_GPU] = max(1, cpu // 16)
                    extra[wk.LABEL_INSTANCE_GPU_NAME] = "a100"
                    extra[wk.LABEL_INSTANCE_GPU_COUNT] = str(extended[wk.RESOURCE_NVIDIA_GPU])
                if fam == "tpu" and cpu >= 8:
                    extended[wk.RESOURCE_TPU] = max(1, cpu // 24)
                    extra[wk.LABEL_INSTANCE_ACCEL_NAME] = f"tpu-v{gen}"
                    extra[wk.LABEL_INSTANCE_ACCEL_COUNT] = str(extended[wk.RESOURCE_TPU])
                # newer generations are slightly cheaper per cpu
                od = round(cpu * price_per_cpu * (1.0 - 0.03 * (gen - gens[0])), 4)
                ovh = node_overhead(cpu_m, mem_b, pods)
                cap = {
                    wk.RESOURCE_CPU: cpu_m,
                    wk.RESOURCE_MEMORY: mem_b,
                    wk.RESOURCE_PODS: pods,
                    wk.RESOURCE_EPHEMERAL: mem_bytes("100Gi"),
                    **extended,
                }
                labels = {
                    wk.LABEL_INSTANCE_TYPE: name,
                    wk.LABEL_ARCH: arch,
                    wk.LABEL_OS: "linux",
                    wk.LABEL_INSTANCE_FAMILY: f"{fam}{gen}",
                    wk.LABEL_INSTANCE_SIZE: size,
                    wk.LABEL_INSTANCE_CPU: str(cpu),
                    wk.LABEL_INSTANCE_MEMORY: str(mem_gib * 1024),
                    wk.LABEL_INSTANCE_PODS: str(pods),
                    wk.LABEL_INSTANCE_HYPERVISOR: "nitro" if gen >= 5 else "xen",
                    **extra,
                }
                offerings = []
                for z in zones:
                    offerings.append(Offering(z, wk.CAPACITY_TYPE_ON_DEMAND, od))
                    offerings.append(Offering(z, wk.CAPACITY_TYPE_SPOT,
                                              round(od * (1 - spot_discount), 4)))
                types.append(InstanceType(
                    name=name,
                    labels=tuple(sorted(labels.items())),
                    capacity=tuple(sorted(cap.items())),
                    overhead=tuple(sorted(ovh.items())),
                    offerings=Offerings(offerings),
                ))
                if max_types and len(types) >= max_types:
                    return Catalog(types=types)
    return Catalog(types=types)


class InstanceTypeProvider:
    """Serves the schedulable instance-type universe with ICE availability
    applied and seqnum-keyed memoization.

    Parity target: InstanceTypeProvider.List (instancetypes.go:92-121):
    result key = catalog seqnum ⊕ ICE-cache seqnum ⊕ template zones hash
    (:104-111), so an ICE mark invalidates instantly ("retry in milliseconds
    instead of minutes").
    """

    def __init__(self, source_catalog: Catalog, unavailable_offerings,
                 subnet_provider=None, settings=None):
        import threading

        self.source = source_catalog
        self.ice = unavailable_offerings
        self.subnets = subnet_provider
        self.settings = settings
        self._memo: "dict[tuple, Catalog]" = {}
        self._version = 0  # monotone seqnum for derived catalogs
        self._lock = threading.Lock()

    def _density_limited(self) -> bool:
        """enableENILimitedPodDensity (settings.go): when disabled, every
        type reports the default max-pods instead of its network-limited
        density. Live-watchable, so it is part of the memo key."""
        return self.settings is None or self.settings.enable_eni_limited_pod_density

    def _vm_overhead_percent(self) -> float:
        """vmMemoryOverheadPercent (settings.go:48,62,83): live-watchable
        memory-overhead fraction. The source catalog bakes the default; a
        changed setting re-derives every type's memory overhead."""
        if self.settings is None:
            return VM_MEMORY_OVERHEAD_PERCENT
        return self.settings.vm_memory_overhead_percent

    def list(self, nodetemplate=None) -> Catalog:
        zones = None
        if nodetemplate is not None and self.subnets is not None and nodetemplate.subnet_selector:
            zones = tuple(self.subnets.zones(nodetemplate.subnet_selector))
        # settings are mutated live by the settings-watch thread: read each
        # knob ONCE so the memo key always matches the catalog built for it
        density = self._density_limited()
        pct = self._vm_overhead_percent()
        pod_eni = self.settings is not None and self.settings.enable_pod_eni
        key = (self.source.seqnum, self.ice.seqnum, zones, density, pct,
               pod_eni)
        with self._lock:
            hit = self._memo.get(key)
            if hit is not None:
                return hit
            # prune dead seqnums AND stale settings variants (pct is an
            # unbounded float dimension); keep only the current settings'
            # per-zones-tuple entries
            for k in [k for k in self._memo
                      if (k[0], k[1], *k[3:]) != (key[0], key[1], *key[3:])]:
                del self._memo[k]
            types = self.ice.apply(self.source.types)
            if pct != VM_MEMORY_OVERHEAD_PERCENT:
                # the SOURCE catalog's baked memory overhead includes the vm
                # share at the DEFAULT percent; a live setting change adjusts
                # by the DELTA only — rebuilding the whole formula would
                # fabricate kube/eviction overhead on fixture catalogs whose
                # baked overhead is not formula-derived
                delta = pct - VM_MEMORY_OVERHEAD_PERCENT
                retuned = []
                for t in types:
                    cap = dict(t.capacity)
                    ovh = dict(t.overhead)
                    ovh[wk.RESOURCE_MEMORY] = max(0, ovh.get(
                        wk.RESOURCE_MEMORY, 0) + int(
                        cap.get(wk.RESOURCE_MEMORY, 0) * delta))
                    retuned.append(_dc.replace(t, overhead=tuple(
                        sorted(ovh.items()))))
                types = retuned
            # enablePodENI (settings.go:79; awsPodENI instancetype.go:
            # 174-181): trunking-compatible (nitro) types advertise vpc
            # pod-eni branch-interface capacity WHEN enabled; disabled
            # STRIPS any baked pod-eni capacity so the gate is symmetric
            # (the reference's disabled path reports quantity 0). The
            # synthetic fleet's rule: nitro types carry min(107, 3*cpu)
            # branches (the reference reads a static per-type limits table).
            gated = []
            for t in types:
                cap = dict(t.capacity)
                if pod_eni:
                    labels = dict(t.labels)
                    if labels.get(wk.LABEL_INSTANCE_HYPERVISOR) == "nitro" \
                            and wk.RESOURCE_POD_ENI not in cap:
                        cpu = int(labels.get(wk.LABEL_INSTANCE_CPU, "0") or 0)
                        cap[wk.RESOURCE_POD_ENI] = min(107, max(1, 3 * cpu))
                        t = _dc.replace(t, capacity=tuple(sorted(cap.items())))
                elif wk.RESOURCE_POD_ENI in cap:
                    del cap[wk.RESOURCE_POD_ENI]
                    t = _dc.replace(t, capacity=tuple(sorted(cap.items())))
                gated.append(t)
            types = gated
            if not density:
                DEFAULT_MAX_PODS = 110
                types = [
                    _dc.replace(t, capacity=tuple(
                        (k, DEFAULT_MAX_PODS if k == wk.RESOURCE_PODS else v)
                        for k, v in t.capacity))
                    for t in types
                ]
            if zones is not None:

                restricted = []
                for t in types:
                    offs = Offerings(o for o in t.offerings if o.zone in zones)
                    if offs:
                        restricted.append(_dc.replace(t, offerings=offs))
                types = restricted
            self._version += 1
            catalog = Catalog(types=types, seqnum=self._version)
            self._memo[key] = catalog
            return catalog

    def livez(self) -> bool:
        """Chained liveness (instancetypes.go:123-131)."""
        return bool(self.source.types)
