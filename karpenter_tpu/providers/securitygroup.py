"""Security-group provider.

Parity target: /root/reference/pkg/providers/securitygroup/securitygroup.go —
List by tag/id selectors -> IDs (:54), 1-minute cache.
"""

from __future__ import annotations

import logging
from typing import Optional

from ..cache import DEFAULT_TTL, TTLCache
from ..utils.clock import Clock

log = logging.getLogger("karpenter.securitygroup")


class SecurityGroupProvider:
    def __init__(self, cloud, clock: Optional[Clock] = None):
        self.cloud = cloud
        self.cache = TTLCache(ttl=DEFAULT_TTL, clock=clock)
        self._last_logged: "tuple | None" = None

    def list(self, selector: "dict[str, str]") -> list:
        key = tuple(sorted(selector.items()))
        hit = self.cache.get(key)
        if hit is not None:
            return hit
        groups = self.cloud.describe_security_groups(selector)
        self.cache.set(key, groups)
        sig = tuple(sorted(g.id for g in groups))
        if self._last_logged != sig:
            self._last_logged = sig
            log.info("discovered security groups: %s", [g.id for g in groups])
        return groups

    def ids(self, selector: "dict[str, str]") -> "list[str]":
        return [g.id for g in self.list(selector)]
