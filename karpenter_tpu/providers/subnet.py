"""Subnet provider.

Parity target: /root/reference/pkg/providers/subnet/subnet.go — List by
tag/id selectors with wildcard support (:57, getFilters :87), 1-minute cache,
change-monitor logging suppression.
"""

from __future__ import annotations

import logging
from typing import Optional

from ..cache import DEFAULT_TTL, TTLCache
from ..utils.clock import Clock

log = logging.getLogger("karpenter.subnet")


class SubnetProvider:
    def __init__(self, cloud, clock: Optional[Clock] = None):
        self.cloud = cloud
        self.cache = TTLCache(ttl=DEFAULT_TTL, clock=clock)
        self._last_logged: "dict[str, tuple]" = {}

    def list(self, selector: "dict[str, str]") -> list:
        key = tuple(sorted(selector.items()))
        hit = self.cache.get(key)
        if hit is not None:
            return hit
        subnets = self.cloud.describe_subnets(selector)
        self.cache.set(key, subnets)
        sig = tuple(sorted(s.id for s in subnets))
        if self._last_logged.get("subnets") != sig:  # ChangeMonitor dedupe (§5.1)
            self._last_logged["subnets"] = sig
            log.info("discovered subnets: %s", [f"{s.id}/{s.zone}" for s in subnets])
        return subnets

    def zones(self, selector: "dict[str, str]") -> "list[str]":
        return sorted({s.zone for s in self.list(selector)})

    def zonal_subnet_with_most_ips(self, selector: "dict[str, str]", zone: str):
        """Pick the zone's subnet with the most free IPs
        (instance.go:326-333 getOverrides)."""
        best = None
        for s in self.list(selector):
            if s.zone != zone:
                continue
            if best is None or s.free_ips > best.free_ips:
                best = s
        return best
