"""Node image discovery + per-family bootstrap generation.

Parity targets:
- AMIProvider — /root/reference/pkg/cloudprovider/amifamily/ami.go: selector
  tag/id filters -> DescribeImages (:158-213), newest-first arch-compatible
  selection (:109-122), default image via SSM parameter per family (:135-149).
- AMIFamily strategy interface — amifamily/resolver.go:72-87 (per-OS-family
  userdata, block devices, feature flags) with concrete families al2 /
  bottlerocket / custom -> here: ubuntu-k8s (shell bootstrap), flatboat
  (TOML settings, the Bottlerocket analogue), custom (raw passthrough).
- Bootstrap generators — amifamily/bootstrap/: kubelet flags, taint
  registration, MIME-multipart merge with user-supplied userdata
  (eksbootstrap.go:52-117,160-224).
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Sequence

from ..apis.nodetemplate import NodeTemplate
from ..cache import TTLCache
from ..models.pod import Taint
from ..utils.clock import Clock

IMAGE_CACHE_TTL = 300.0


@dataclasses.dataclass
class ResolvedImage:
    image_id: str
    arch: str


@dataclasses.dataclass
class BootstrapConfig:
    cluster_name: str
    cluster_endpoint: str
    ca_bundle: str = ""
    dns_ip: str = ""
    labels: "dict[str, str]" = dataclasses.field(default_factory=dict)
    taints: "tuple[Taint, ...]" = ()
    # full kubelet config: the node's real kubelet must enforce exactly what
    # the scheduler modeled (max-pods/pods-per-core/reserved/eviction)
    kubelet: "Optional[object]" = None  # apis.provisioner.KubeletConfiguration
    custom_userdata: str = ""

    def kubelet_flags(self) -> "list[str]":
        """kubelet CLI flags for the shell-bootstrap family; TOML families
        render the same fields their own way."""
        k = self.kubelet
        if k is None:
            return []
        flags = []
        if k.max_pods is not None:
            flags.append(f"--max-pods={k.max_pods}")
        if k.pods_per_core is not None:
            flags.append(f"--pods-per-core={k.pods_per_core}")
        reserved = []
        if k.system_reserved_cpu_millis:
            reserved.append(f"cpu={k.system_reserved_cpu_millis}m")
        if k.system_reserved_memory_bytes:
            reserved.append(f"memory={k.system_reserved_memory_bytes}")
        if reserved:
            flags.append(f"--system-reserved={','.join(reserved)}")
        kube_res = []
        if k.kube_reserved_cpu_millis is not None:
            kube_res.append(f"cpu={k.kube_reserved_cpu_millis}m")
        if k.kube_reserved_memory_bytes is not None:
            kube_res.append(f"memory={k.kube_reserved_memory_bytes}")
        if kube_res:
            flags.append(f"--kube-reserved={','.join(kube_res)}")
        if k.eviction_hard_memory_bytes:
            flags.append(f"--eviction-hard=memory.available<{k.eviction_hard_memory_bytes}")
        # bootstrap passthrough (reference CRD kubeletConfiguration keys
        # with no scheduling impact — they only shape the node's kubelet)
        if k.cluster_dns:
            flags.append(f"--cluster-dns={','.join(k.cluster_dns)}")
        if k.container_runtime is not None:
            flags.append(f"--container-runtime={k.container_runtime}")
        if k.cpu_cfs_quota is not None:
            flags.append(f"--cpu-cfs-quota={str(k.cpu_cfs_quota).lower()}")
        if k.eviction_soft:
            flags.append("--eviction-soft=" + ",".join(
                f"{sig}<{val}" for sig, val in k.eviction_soft))
        if k.eviction_soft_grace_period:
            flags.append("--eviction-soft-grace-period=" + ",".join(
                f"{sig}={val}" for sig, val in k.eviction_soft_grace_period))
        if k.eviction_max_pod_grace_period is not None:
            flags.append("--eviction-max-pod-grace-period="
                         f"{k.eviction_max_pod_grace_period}")
        if k.image_gc_high_threshold_percent is not None:
            flags.append("--image-gc-high-threshold="
                         f"{k.image_gc_high_threshold_percent}")
        if k.image_gc_low_threshold_percent is not None:
            flags.append("--image-gc-low-threshold="
                         f"{k.image_gc_low_threshold_percent}")
        return flags


class ImageFamily:
    """Strategy per image family (AMIFamily iface, resolver.go:72-79)."""

    name = "base"

    def default_image_parameter(self, arch: str) -> str:
        return f"/karpenter-tpu/images/default/{arch}/latest"

    def userdata(self, cfg: BootstrapConfig) -> str:
        raise NotImplementedError


class UbuntuK8s(ImageFamily):
    """Shell bootstrap family (EKS AL2 bootstrap.sh analogue)."""

    name = "ubuntu-k8s"

    def userdata(self, cfg: BootstrapConfig) -> str:
        flags = [f"--node-labels={','.join(f'{k}={v}' for k, v in sorted(cfg.labels.items()))}"]
        if cfg.taints:
            taints = ",".join(f"{t.key}={t.value}:{t.effect}" for t in cfg.taints)
            flags.append(f"--register-with-taints={taints}")
        flags.extend(cfg.kubelet_flags())
        script = "\n".join([
            "#!/bin/bash -xe",
            f"/etc/node/bootstrap.sh '{cfg.cluster_name}' \\",
            f"  --apiserver-endpoint '{cfg.cluster_endpoint}' \\",
            f"  --b64-cluster-ca '{cfg.ca_bundle}' \\",
            f"  --dns-cluster-ip '{cfg.dns_ip}' \\",
            f"  --kubelet-extra-args '{' '.join(flags)}'",
        ])
        if cfg.custom_userdata:
            # MIME multipart merge: custom part first, bootstrap last
            # (eksbootstrap.go:160-224 merge semantics)
            boundary = "//KARPENTER-TPU-BOUNDARY//"
            return "\n".join([
                'MIME-Version: 1.0',
                f'Content-Type: multipart/mixed; boundary="{boundary}"',
                "",
                f"--{boundary}",
                'Content-Type: text/x-shellscript; charset="us-ascii"',
                "",
                cfg.custom_userdata,
                f"--{boundary}",
                'Content-Type: text/x-shellscript; charset="us-ascii"',
                "",
                script,
                f"--{boundary}--",
            ])
        return script


class Flatboat(ImageFamily):
    """TOML-settings family (Bottlerocket analogue, bottlerocketsettings.go)."""

    name = "flatboat"

    def userdata(self, cfg: BootstrapConfig) -> str:
        lines = [
            "[settings.kubernetes]",
            f'cluster-name = "{cfg.cluster_name}"',
            f'api-server = "{cfg.cluster_endpoint}"',
        ]
        if cfg.ca_bundle:
            lines.append(f'cluster-certificate = "{cfg.ca_bundle}"')
        if cfg.dns_ip:
            lines.append(f'cluster-dns-ip = "{cfg.dns_ip}"')
        k = cfg.kubelet
        if k is not None:
            if k.max_pods is not None:
                lines.append(f"max-pods = {k.max_pods}")
            if k.pods_per_core is not None:
                lines.append(f"pods-per-core = {k.pods_per_core}")
            # passthrough keys render TOML-style too (the kubelet_flags
            # docstring's contract: TOML families carry the same fields)
            if k.cluster_dns:
                lines.append(f'cluster-dns-ip = "{k.cluster_dns[0]}"')
            if k.cpu_cfs_quota is not None:
                lines.append(
                    f"cpu-cfs-quota-enforced = {str(k.cpu_cfs_quota).lower()}")
            if k.eviction_max_pod_grace_period is not None:
                lines.append("eviction-max-pod-grace-period = "
                             f"{k.eviction_max_pod_grace_period}")
            if k.image_gc_high_threshold_percent is not None:
                lines.append("image-gc-high-threshold-percent = "
                             f'"{k.image_gc_high_threshold_percent}"')
            if k.image_gc_low_threshold_percent is not None:
                lines.append("image-gc-low-threshold-percent = "
                             f'"{k.image_gc_low_threshold_percent}"')
            if k.system_reserved_cpu_millis or k.system_reserved_memory_bytes:
                lines.append("[settings.kubernetes.system-reserved]")
                if k.system_reserved_cpu_millis:
                    lines.append(f'cpu = "{k.system_reserved_cpu_millis}m"')
                if k.system_reserved_memory_bytes:
                    lines.append(f'memory = "{k.system_reserved_memory_bytes}"')
            if k.eviction_soft:
                lines.append("[settings.kubernetes.eviction-soft]")
                lines += [f'"{sig}" = "{val}"' for sig, val in k.eviction_soft]
            if k.eviction_soft_grace_period:
                lines.append("[settings.kubernetes.eviction-soft-grace-period]")
                lines += [f'"{sig}" = "{val}"'
                          for sig, val in k.eviction_soft_grace_period]
        if cfg.labels:
            lines.append("[settings.kubernetes.node-labels]")
            lines += [f'"{k}" = "{v}"' for k, v in sorted(cfg.labels.items())]
        if cfg.taints:
            lines.append("[settings.kubernetes.node-taints]")
            lines += [f'"{t.key}" = "{t.value}:{t.effect}"' for t in cfg.taints]
        base = "\n".join(lines)
        if cfg.custom_userdata:
            # custom TOML is merged after ours (later keys win)
            return base + "\n" + cfg.custom_userdata
        return base


class Custom(ImageFamily):
    """Raw userdata passthrough (amifamily/custom.go)."""

    name = "custom"

    def userdata(self, cfg: BootstrapConfig) -> str:
        return cfg.custom_userdata


FAMILIES = {f.name: f for f in (UbuntuK8s(), Flatboat(), Custom())}


def get_family(name: str) -> ImageFamily:
    """GetAMIFamily with default fallback (resolver.go:143-154)."""
    return FAMILIES.get(name, FAMILIES["ubuntu-k8s"])


class ImageProvider:
    def __init__(self, cloud, clock: Optional[Clock] = None):
        self.cloud = cloud
        self.cache = TTLCache(ttl=IMAGE_CACHE_TTL, clock=clock)

    def get(self, template: NodeTemplate, archs: Sequence[str]) -> "list[ResolvedImage]":
        """Resolve images for a NodeTemplate: selector-based discovery
        (newest first per arch) or the family's default SSM alias."""
        key = (template.name, template.generation, tuple(archs))
        hit = self.cache.get(key)
        if hit is not None:
            return hit
        out: "list[ResolvedImage]" = []
        if template.image_selector:
            images = self.cloud.describe_images(template.image_selector)
            for arch in archs:
                compat = sorted((i for i in images if i.arch == arch),
                                key=lambda i: -i.created)  # newest first (:109-122)
                if compat:
                    out.append(ResolvedImage(image_id=compat[0].id, arch=arch))
        else:
            family = get_family(template.image_family)
            for arch in archs:
                try:
                    image_id = self.cloud.get_ssm_parameter(
                        family.default_image_parameter(arch))
                except Exception:
                    continue
                out.append(ResolvedImage(image_id=image_id, arch=arch))
        if out:
            # never cache an empty resolution: one transient backend failure
            # must not block launches for a whole TTL window
            self.cache.set(key, out)
        return out
