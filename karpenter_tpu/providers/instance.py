"""Instance lifecycle provider.

Parity target: /root/reference/pkg/cloudprovider/instance.go —
- Create (:82-116): filter instance types (exotic-type drop :532-553,
  spot-above-cheapest-on-demand drop :505-527), order by price and truncate
  to MaxInstanceTypes=60 (:84-87 + cloudprovider.go:58-60), launch.
- launchInstance (:212-265): capacity-type choice (spot iff allowed and
  offered, :430-443), EnsureAll launch templates, overrides = offerings x
  zonal-subnet-with-most-free-IPs (:325-373), batched CreateFleet, ICE
  errors -> UnavailableOfferings (:419-425), LT-not-found single retry with
  cache invalidation (:90-94, 248-252).
- Get/List by cluster+machine tags (:119-174), Delete via batched
  TerminateInstances (:181-210), OD-flexibility warning (>=5 types, :52,
  267-287).
"""

from __future__ import annotations

import logging
from typing import Optional, Sequence

from ..apis import wellknown as wk
from ..apis.nodetemplate import NodeTemplate
from ..apis.settings import Settings
from ..batcher.fleet import (
    CreateFleetBatcher, DescribeInstancesBatcher, TerminateInstancesBatcher,
)
from ..cache import UnavailableOfferings
from ..fake.cloud import CloudInstance, CreateFleetRequest, FleetOverride
from ..models.instancetype import InstanceType
from ..models.machine import Machine
from ..models.requirements import Requirements
from ..utils import errors as cloud_errors
from .launchtemplate import LaunchTemplateProvider
from .subnet import SubnetProvider

log = logging.getLogger("karpenter.instance")

MAX_INSTANCE_TYPES = 60  # cloudprovider.go:58-60
MIN_OD_FLEXIBILITY = 5   # instance.go:52

TAG_CLUSTER = "karpenter.sh/cluster"
TAG_MACHINE = "karpenter.sh/machine"
TAG_PROVISIONER = "karpenter.sh/provisioner-name"


class InstanceProvider:
    def __init__(
        self,
        cloud,
        settings: Settings,
        launch_templates: LaunchTemplateProvider,
        subnets: SubnetProvider,
        unavailable_offerings: UnavailableOfferings,
        fleet_batcher: Optional[CreateFleetBatcher] = None,
        describe_batcher: Optional[DescribeInstancesBatcher] = None,
        terminate_batcher: Optional[TerminateInstancesBatcher] = None,
        policy=None,
    ):
        self.cloud = cloud
        self.settings = settings
        self.launch_templates = launch_templates
        self.subnets = subnets
        self.ice = unavailable_offerings
        # one shared resilience.RetryPolicy for the cloud-API edge: all
        # three batchers spend from the same retry budget and feed the
        # same breaker (they ARE the same dependency)
        self.fleet = fleet_batcher or CreateFleetBatcher(cloud, policy=policy)
        self.describe = describe_batcher or DescribeInstancesBatcher(
            cloud, policy=policy)
        self.terminate = terminate_batcher or TerminateInstancesBatcher(
            cloud, policy=policy)

    # -- create ----------------------------------------------------------------

    def create(self, template: NodeTemplate, machine: Machine,
               instance_types: "list[InstanceType]") -> CloudInstance:
        types = self.filter_instance_types(
            instance_types, machine.spec.requirements, machine.spec.resource_requests)
        types = order_by_price(types, machine.spec.requirements)[:MAX_INSTANCE_TYPES]
        if not types:
            raise cloud_errors.CloudError(
                "UnfulfillableCapacity", "no instance types satisfy the machine")
        capacity_type = self.get_capacity_type(machine, types)
        if capacity_type == wk.CAPACITY_TYPE_ON_DEMAND and len(types) < MIN_OD_FLEXIBILITY:
            log.warning("launching with on-demand flexibility %d < %d recommended",
                        len(types), MIN_OD_FLEXIBILITY)
        try:
            return self._launch(template, machine, types, capacity_type)
        except cloud_errors.CloudError as e:
            if cloud_errors.is_launch_template_not_found(e):
                # single retry after invalidation (instance.go:90-94)
                return self._launch(template, machine, types, capacity_type)
            raise

    def _launch(self, template: NodeTemplate, machine: Machine,
                types: "list[InstanceType]", capacity_type: str) -> CloudInstance:
        labels = {k: v for k, v in machine.labels.items()}
        lts = self.launch_templates.ensure_all(
            template, labels=labels,
            # the node registers with BOTH taint sets; startup taints are
            # cleared at initialization (machinelifecycle controller)
            taints=tuple(machine.spec.taints) + tuple(machine.spec.startup_taints),
            archs=self._archs(types), kubelet=machine.spec.kubelet)
        if not lts:
            raise cloud_errors.CloudError(
                "ResourceNotFound",
                f"no images resolved for template {template.name}")
        # arch -> launch template so every override boots the right image
        # (multi-arch fleet, getLaunchTemplateConfigs instance.go:289-323)
        arch_to_lt = {arch: name for name, archs in lts.items() for arch in archs}
        overrides = self.get_overrides(template, types, capacity_type,
                                       machine.spec.requirements, arch_to_lt)
        if not overrides:
            raise cloud_errors.CloudError(
                "UnfulfillableCapacity", "no offering x subnet overrides")
        # machine-specific tags are applied AFTER launch: the fleet request
        # must be identical across machines of one provisioning round, or the
        # batcher can never merge them (createfleet.go merge contract) —
        # callers are associated with instances by the returned IDs instead.
        tags = {
            TAG_CLUSTER: self.settings.cluster_name,
            TAG_PROVISIONER: machine.spec.provisioner_name,
            f"kubernetes.io/cluster/{self.settings.cluster_name}": "owned",
            **self.settings.tags, **template.tags,
        }
        request = CreateFleetRequest(
            launch_template=next(iter(lts)), overrides=overrides, capacity=1,
            capacity_type=capacity_type, tags=tags,
            fleet_context=template.fleet_context)
        try:
            resp = self.fleet.create_fleet(request)
        except cloud_errors.FleetError as e:
            if cloud_errors.is_unfulfillable_capacity(e):
                self.ice.mark_unavailable_for_fleet_err(e, capacity_type)
            raise
        except cloud_errors.CloudError as e:
            if cloud_errors.is_launch_template_not_found(e):
                for name in lts:
                    self.launch_templates.invalidate(name)
            raise
        for err in resp.errors:  # partial pool failures still poison the cache
            self.ice.mark_unavailable(err.code, err.instance_type, err.zone,
                                      capacity_type)
        instance_id = resp.instance_ids[0]
        self.cloud.create_tags(instance_id, {TAG_MACHINE: machine.name})
        instance = self.get_by_id(instance_id)
        return instance

    @staticmethod
    def _archs(types: "list[InstanceType]") -> "list[str]":
        return sorted({t.labels_dict().get(wk.LABEL_ARCH, "amd64") for t in types})

    def filter_instance_types(self, types: "list[InstanceType]", reqs: Requirements,
                              resource_requests: "dict[str, int] | None" = None,
                              ) -> "list[InstanceType]":
        """Drop spot offerings priced above the cheapest on-demand
        (instance.go:505-527) and exotic types unless explicitly requested
        (:532-553 — here: accelerator types are exotic unless the machine
        requests the resource)."""
        resource_requests = resource_requests or {}
        wants_accel = {
            r for r in (wk.RESOURCE_NVIDIA_GPU, wk.RESOURCE_AMD_GPU, wk.RESOURCE_TPU,
                        wk.RESOURCE_NEURON, wk.RESOURCE_GAUDI)
        }
        ct_req = reqs.get(wk.LABEL_CAPACITY_TYPE)
        spot_allowed = ct_req is None or ct_req.has(wk.CAPACITY_TYPE_SPOT)
        od_allowed = ct_req is None or ct_req.has(wk.CAPACITY_TYPE_ON_DEMAND)
        cheapest_od = min(
            (o.price for t in types for o in t.offerings.available()
             if o.capacity_type == wk.CAPACITY_TYPE_ON_DEMAND),
            default=None)
        out = []
        for t in types:
            caps = dict(t.capacity)
            is_exotic = any(caps.get(r, 0) > 0 for r in wants_accel)
            if is_exotic:
                requested = any(resource_requests.get(r, 0) > 0
                                for r in wants_accel if caps.get(r, 0) > 0)
                if not requested:
                    continue
            if (spot_allowed and od_allowed and cheapest_od is not None):
                spot_offs = [o for o in t.offerings.available()
                             if o.capacity_type == wk.CAPACITY_TYPE_SPOT]
                if spot_offs and all(o.price >= cheapest_od for o in spot_offs):
                    od_offs = [o for o in t.offerings.available()
                               if o.capacity_type == wk.CAPACITY_TYPE_ON_DEMAND]
                    if not od_offs:
                        continue
            out.append(t)
        return out

    def get_capacity_type(self, machine: Machine,
                          types: "list[InstanceType]") -> str:
        """spot iff allowed by requirements AND offered by >=1 candidate
        (instance.go:430-443)."""
        ct_req = machine.spec.requirements.get(wk.LABEL_CAPACITY_TYPE)
        if ct_req is None or ct_req.has(wk.CAPACITY_TYPE_SPOT):
            for t in types:
                for o in t.offerings.available():
                    if o.capacity_type == wk.CAPACITY_TYPE_SPOT:
                        return wk.CAPACITY_TYPE_SPOT
        return wk.CAPACITY_TYPE_ON_DEMAND

    def get_overrides(self, template: NodeTemplate, types: "list[InstanceType]",
                      capacity_type: str, reqs: Requirements,
                      arch_to_lt: "dict[str, str] | None" = None,
                      ) -> "list[FleetOverride]":
        """offerings x zonal subnets cross product (instance.go:325-373)."""
        zone_req = reqs.get(wk.LABEL_ZONE)
        overrides: "list[FleetOverride]" = []
        for t in types:
            arch = t.labels_dict().get(wk.LABEL_ARCH, "amd64")
            lt = (arch_to_lt or {}).get(arch, "")
            if arch_to_lt is not None and not lt:
                continue  # no image for this arch -> type not launchable
            for o in t.offerings.available():
                if o.capacity_type != capacity_type:
                    continue
                if zone_req is not None and not zone_req.has(o.zone):
                    continue
                if self.ice.is_unavailable(capacity_type, t.name, o.zone):
                    continue
                subnet = self.subnets.zonal_subnet_with_most_ips(
                    template.subnet_selector, o.zone)
                if subnet is None:
                    continue
                overrides.append(FleetOverride(
                    instance_type=t.name, zone=o.zone, subnet_id=subnet.id,
                    price=o.price, launch_template=lt))
        return overrides

    # -- read / delete ---------------------------------------------------------

    def get_by_id(self, instance_id: str) -> CloudInstance:
        return self.describe.describe(instance_id)

    def get_by_machine(self, machine_name: str) -> Optional[CloudInstance]:
        found = self.cloud.describe_instances_by_tag(TAG_MACHINE, machine_name)
        if not found:
            return None
        # double-launch race: keep the newest, delete the rest
        # (instance.go:176-192 tag-scoped Get-then-Delete)
        found.sort(key=lambda i: -i.launch_time)
        for stale in found[1:]:
            try:
                self.terminate.terminate(stale.id)
            except cloud_errors.CloudError:
                pass
        return found[0]

    def list_cluster_instances(self) -> "list[CloudInstance]":
        return self.cloud.describe_instances_by_tag(
            TAG_CLUSTER, self.settings.cluster_name)

    def delete(self, instance_id: str) -> None:
        try:
            self.terminate.terminate(instance_id)
        except cloud_errors.CloudError as e:
            if not cloud_errors.is_not_found(e):
                raise

    def stop(self):
        self.fleet.stop()
        self.describe.stop()
        self.terminate.stop()


def order_by_price(types: "list[InstanceType]", reqs: Requirements) -> "list[InstanceType]":
    """Price-ascending order under the machine requirements
    (instance.go:445-462 orderInstanceTypesByPrice)."""
    return sorted(types, key=lambda t: (t.cheapest_price(reqs), t.name))
