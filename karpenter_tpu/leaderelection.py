"""Store-backed lease leader election (active/passive HA).

Parity target: the reference runs 2 replicas + PDB with real lease-based
leader election through the operator manager
(/root/reference/cmd/controller/main.go:34,42 `operator.NewOperator` with
LEADER_ELECT, charts/karpenter 2-replica deployment). Controllers act only
on the elected replica; a standby takes over within the lease TTL when the
leader dies, and immediately when it releases gracefully.

The lease lives in the coordination plane (KubeStore kind "leases" — the
coordination.k8s.io/Lease analogue) and every transition is a single
compare-and-swap, so two candidates racing a renewal or a takeover cannot
both win (kube.compare_and_swap raises Conflict for the loser).
"""

from __future__ import annotations

import dataclasses
import logging
import threading
from typing import Callable, Optional

from .fake.kube import Conflict
from .utils.clock import Clock

log = logging.getLogger("karpenter.leaderelection")

LEASE_NAME = "karpenter-leader"


@dataclasses.dataclass(frozen=True)
class Lease:
    """coordination.k8s.io/v1 Lease spec subset."""

    holder: str
    acquired_ts: float   # when the current holder first became leader
    renew_ts: float      # last successful renewal
    duration_s: float    # holder is presumed dead duration_s after renew_ts
    # fencing token: strictly increasing across leadership changes (renewals
    # keep it). The store tracks the highest epoch it has seen, so a deposed
    # leader's late writes — presented with the old epoch — are rejected
    # (docs/designs/recovery.md, fencing semantics).
    epoch: int = 0

    def expired(self, now: float) -> bool:
        return now - self.renew_ts >= self.duration_s


class LeaderElector:
    """Acquire/renew loop with standby takeover.

    - the holder renews every `renew_period_s` (< duration/2 by default);
    - a standby polls and takes over once the lease expires;
    - `release()` (graceful shutdown) deletes the lease iff still ours, so
      the standby flips without waiting out the TTL;
    - losing a renewal race or failing to renew within the TTL demotes the
      local process immediately (elected cleared before callbacks fire).
    """

    def __init__(self, kube, identity: str, clock: Optional[Clock] = None,
                 lease_duration_s: float = 15.0, renew_period_s: float = 4.0,
                 retry_period_s: float = 2.0, name: str = LEASE_NAME,
                 on_started_leading: "Optional[Callable[[], None]]" = None,
                 on_stopped_leading: "Optional[Callable[[], None]]" = None):
        self.kube = kube
        self.identity = identity
        self.clock = clock or Clock()
        self.lease_duration_s = lease_duration_s
        self.renew_period_s = renew_period_s
        self.retry_period_s = retry_period_s
        self.name = name
        self.elected = threading.Event()
        self._on_started = on_started_leading
        self._on_stopped = on_stopped_leading
        self._held: "Optional[Lease]" = None  # our last written lease object
        # serializes tick vs release: a release racing an in-flight renewal
        # could otherwise leave the fresh lease dangling (or resurrect it)
        self._mutex = threading.Lock()

    def is_leader(self) -> bool:
        return self.elected.is_set()

    def fencing_token(self) -> "Optional[int]":
        """The epoch of the lease this elector believes it holds, or None.
        Deliberately returns the (possibly stale) epoch while deposed-but-
        unaware: that IS the zombie write the store must reject."""
        held = self._held
        return held.epoch if held is not None else None

    # -- one election tick -----------------------------------------------------

    def try_acquire_or_renew(self) -> bool:
        """One CAS-guarded tick; returns leadership after the tick."""
        with self._mutex:
            return self._tick()

    def _tick(self) -> bool:
        now = self.clock.now()
        cur = self.kube.get("leases", self.name)
        try:
            if cur is None:
                fresh = Lease(self.identity, now, now, self.lease_duration_s,
                              epoch=self._next_epoch(cur))
                self.kube.create("leases", self.name, fresh)
                self._became_leader(fresh, takeover_from=None)
            elif cur.holder == self.identity:
                renewed = dataclasses.replace(cur, renew_ts=now)
                self.kube.compare_and_swap("leases", self.name, cur, renewed)
                self._held = renewed
                if not self.elected.is_set():  # e.g. restart with stale lease
                    self._became_leader(renewed, takeover_from=None)
            elif cur.expired(now):
                taken = Lease(self.identity, now, now, self.lease_duration_s,
                              epoch=self._next_epoch(cur))
                self.kube.compare_and_swap("leases", self.name, cur, taken)
                self._became_leader(taken, takeover_from=cur.holder)
            else:
                self._demote_if_leading("lease held by %s" % cur.holder)
        except Conflict:
            # another candidate won this write; if we thought we were the
            # leader our lease was stolen (we must have been expired)
            self._demote_if_leading("lost lease race")
        return self.elected.is_set()

    def _next_epoch(self, cur: "Optional[Lease]") -> int:
        """Mint a fencing epoch strictly above every epoch the store has
        observed — a gracefully released lease is gone, so `cur` alone
        can't carry the high-water mark."""
        prev = getattr(cur, "epoch", 0) if cur is not None else 0
        fence = getattr(self.kube, "fence_epoch", None)
        if callable(fence):
            try:
                prev = max(prev, fence())
            except Exception:
                pass
        return prev + 1

    def release(self) -> None:
        """Graceful handoff: delete the lease iff it is still ours.

        Consults the STORE, not `_held`: an error-path demotion (store
        hiccup mid-renewal) clears `_held` while our lease object survives
        in the store — an early-return on `_held is None` would strand that
        lease and force the standby to wait out the full TTL."""
        with self._mutex:
            cur = self.kube.get("leases", self.name)
            if cur is not None and cur.holder == self.identity:
                self.kube.delete_if("leases", self.name, cur)
            self._demote_if_leading("released")

    def _became_leader(self, lease: Lease, takeover_from: "Optional[str]") -> None:
        self._held = lease
        if not self.elected.is_set():
            if takeover_from:
                log.info("%s took leadership over from expired %s",
                         self.identity, takeover_from)
            else:
                log.info("%s became leader", self.identity)
            self.elected.set()
            if self._on_started is not None:
                self._on_started()

    def _demote_if_leading(self, why: str) -> None:
        self._held = None
        if self.elected.is_set():
            log.warning("%s lost leadership (%s)", self.identity, why)
            self.elected.clear()
            if self._on_stopped is not None:
                self._on_stopped()

    # -- loop ------------------------------------------------------------------

    def run(self, stop_event: threading.Event) -> None:
        while not stop_event.is_set():
            try:
                leading = self.try_acquire_or_renew()
            except Exception as e:  # store hiccup: drop leadership, retry
                log.exception("election tick failed: %s", e)
                self._demote_if_leading(f"election error: {e}")
                leading = False
            stop_event.wait(self.renew_period_s if leading
                            else self.retry_period_s)
        self.release()
