"""Global on/off switch for the spot resilience plane.

The spot plane is advisory-never-load-bearing (same contract as the
profiling/explain/membership/incremental planes): every producer — the
interruption forecaster, the risk-aware objective, the proactive
rebalance controller — checks :func:`enabled` before doing ANY work, so
disabling the plane is a strict no-op (zero counters, penalty factors
pinned at 1.0, no diversity mask, no proactive drains — every solve is
bit-identical to a build without the plane). The chaos drill enforces
exactly that invariant (``spot-strict-noop``) with two-window evidence:
activity counters frozen while disabled AND solve decisions identical to
the baseline.

Default is ON (forecasts are advisory and cheap); ``KARPENTER_TPU_SPOT=0``
(or ``false``/``off``/``no``) disables it at process start, and
:func:`set_enabled` / :func:`disabled` flip it at runtime (chaos drills,
A/B cost baselines).
"""
from __future__ import annotations

import contextlib
import os
import threading

FLAG_ENV = "KARPENTER_TPU_SPOT"
_FALSY = ("0", "false", "off", "no")

_lock = threading.Lock()
_enabled = os.environ.get(FLAG_ENV, "1").strip().lower() not in _FALSY


def enabled() -> bool:
    return _enabled


def set_enabled(on: bool) -> bool:
    """Flip the plane; returns the previous state (restore token)."""
    global _enabled
    with _lock:
        prev = _enabled
        _enabled = bool(on)
        return prev


@contextlib.contextmanager
def disabled():
    """Scoped hard-off: A/B baselines and the chaos strict-noop drill."""
    prev = set_enabled(False)
    try:
        yield
    finally:
        set_enabled(prev)
