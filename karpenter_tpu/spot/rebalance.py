"""Proactive spot rebalance: drain ahead of the reclaim, not after it.

When the forecaster predicts elevated interruption risk for a spot pool
(rate ≥ ``REBALANCE_RATE_THRESHOLD``), this controller moves capacity off
the at-risk nodes BEFORE the platform reclaims them, through the same
two-phase shape as consolidation's replace path (deprovisioning.py):
launch the replacement first, drain the old node only once the
replacement is initialized — pods never pass through a pending window,
so a crash or a mispredicted storm can never strand workload.

Guard rails, in order of precedence:

1. **Never strands pods** — phase 2 (the drain) only fires when the
   replacement is live and initialized; a replacement that dies or times
   out is rolled back and the at-risk node keeps running (reactive
   interruption handling still covers it).
2. **Cost never raised** — a replacement is only considered if a pool
   with forecast rate BELOW the threshold exists at a real (sticker)
   price ≤ the at-risk node's price. No safe pool at equal-or-lower
   cost ⇒ skip (counted), defer to reactive handling.
3. **Churn ≤ risk avoided** — :class:`RebalanceRateLimiter` banks the
   predicted-interruption mass (Σ forecast rates over at-risk nodes) as
   tokens; each proactive drain spends one. Lifetime drains can never
   exceed lifetime predicted-interruption mass, and the bank zeroes the
   moment the forecast clears — a wrong forecaster stops causing churn
   within one cycle (the chaos forecaster-was-wrong schedule audits
   exactly this).

Every phase journals through the recovery plane (``REBALANCE`` intent
records): a crash mid-rebalance rolls forward (workload already on the
replacement) or back (empty replacement reaped) on the next incarnation.
Strict-noop under ``KARPENTER_TPU_SPOT=0``: reconcile returns before
touching any counter, journal, or node.
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Optional

from .. import explain
from ..apis import wellknown as wk
from ..events import EventRecorder
from ..introspect.watchdog import cycle as _wd_cycle
from ..metrics import NAMESPACE, REGISTRY, Registry
from ..recovery.crashpoints import crashpoint
from ..recovery.journal import REBALANCE
from ..utils.clock import Clock
from . import state
from .forecaster import REBALANCE_RATE_THRESHOLD

log = logging.getLogger("karpenter.spot")

_counters_lock = threading.Lock()
_COUNTERS = {
    "spot_rebalance_cycles": 0,
    "spot_rebalance_launched": 0,
    "spot_rebalance_drained": 0,
    "spot_rebalance_rate_limited": 0,
    "spot_rebalance_no_safe_pool": 0,
    "spot_rebalance_rolled_back": 0,
    "spot_rebalance_overtaken": 0,
}


def _count(key: str, n: int = 1) -> None:
    with _counters_lock:
        _COUNTERS[key] += n


def counters() -> "dict[str, int]":
    with _counters_lock:
        return dict(_COUNTERS)


class RebalanceRateLimiter:
    """Token bank encoding "churn never exceeds the interruption rate it
    avoids": `accrue(mass)` deposits the cycle's predicted-interruption
    mass (Σ forecast rates over currently at-risk nodes, capped at a
    small burst), each drain spends 1.0. Lifetime ``spent`` ≤ lifetime
    ``accrued`` by construction (the property test falsifies this with
    adversarial accrual schedules), and a cycle with zero at-risk mass
    ZEROES the bank — a cleared forecast stops proactive churn at the
    next reconcile, banked history notwithstanding."""

    BURST = 2.0  # bank at most this many cycles' worth of mass

    def __init__(self):
        self.tokens = 0.0
        self.accrued = 0.0
        self.spent = 0

    def accrue(self, mass: float) -> int:
        """Deposit one cycle's at-risk mass; returns the whole-drain
        budget now affordable."""
        if mass <= 0.0:
            self.tokens = 0.0
            return 0
        deposit = min(mass, max(self.BURST * mass - self.tokens, 0.0))
        self.tokens += deposit
        self.accrued += deposit
        return int(self.tokens)

    def spend(self, n: int = 1) -> None:
        self.tokens = max(0.0, self.tokens - n)
        self.spent += n

    def snapshot(self) -> dict:
        return {"tokens": round(self.tokens, 6),
                "accrued": round(self.accrued, 6),
                "spent": self.spent}


class RebalanceController:
    """One proactive rebalance in flight at a time (the deprovisioning
    single-action-per-cycle discipline), driven from the operator loop
    and the chaos drill alike."""

    REBALANCE_INIT_TIMEOUT_S = 300.0

    def __init__(self, kube, cloudprovider, cluster, termination,
                 provisioning, forecaster,
                 clock: "Optional[Clock]" = None,
                 recorder: "Optional[EventRecorder]" = None,
                 registry: "Optional[Registry]" = None,
                 journal=None, watchdog=None):
        self.kube = kube
        self.cloudprovider = cloudprovider
        self.cluster = cluster
        self.termination = termination
        self.provisioning = provisioning
        self.forecaster = forecaster
        self.clock = clock or Clock()
        self.recorder = recorder or EventRecorder(clock=self.clock)
        self.journal = journal
        self.watchdog = watchdog
        self.limiter = RebalanceRateLimiter()
        self._pending: "Optional[dict]" = None
        # per-action cost ledger: the cost-never-raised guarantee is by
        # construction (_safe_offering), but the storm drill audits the
        # receipts — every replacement's sticker price vs the node it
        # relieves (chaos/invariants.check_spot_cost_never_raised)
        self.ledger: "list[dict]" = []
        reg = registry or REGISTRY
        self.actions = reg.counter(
            f"{NAMESPACE}_spot_rebalance_actions_total",
            "Proactive spot rebalance actions.", ("action",))
        self.budget_gauge = reg.gauge(
            f"{NAMESPACE}_spot_rebalance_budget",
            "Rebalance drains currently affordable under the "
            "churn-le-risk-avoided token bank.")
        # the SAME family the interruption controller registers — the
        # registry returns the existing metric, so reactive and proactive
        # drains land in one histogram split by `reason`
        self.drain_throughput = reg.histogram(
            f"{NAMESPACE}_interruption_drain_throughput_msgs_per_second",
            "Messages drained per second, per receive batch "
            "(handle + delete, wall time), by drain reason.", ("reason",),
            buckets=(50, 100, 250, 500, 1000, 2500, 5000, 10000))

    # -- reconcile -------------------------------------------------------------

    def reconcile_once(self) -> int:
        with _wd_cycle(self.watchdog, "spotrebalance"):
            return self._reconcile_once()

    def _reconcile_once(self) -> int:
        if not state.enabled():
            return 0
        _count("spot_rebalance_cycles")
        now = self.clock.now()
        if self._pending is not None:
            return self._finish_pending(now)
        at_risk = self._at_risk_nodes()
        mass = sum(rate for _, rate in at_risk)
        budget = self.limiter.accrue(mass)
        self.budget_gauge.set(budget)
        if not at_risk:
            return 0
        if budget < 1:
            _count("spot_rebalance_rate_limited")
            self.actions.inc(action="rate-limited")
            return 0
        # highest predicted risk first; name tiebreak keeps the drill
        # deterministic
        for node, rate in sorted(at_risk, key=lambda p: (-p[1], p[0].name)):
            if self._begin_rebalance(node, rate, now):
                return 1
        return 0

    def _at_risk_nodes(self) -> "list[tuple[object, float]]":
        out = []
        for name in sorted(self.cluster.nodes):
            node = self.cluster.nodes[name]
            if node.capacity_type != wk.CAPACITY_TYPE_SPOT:
                continue
            if node.marked_for_deletion or not node.initialized:
                continue
            rate = self.forecaster.rate(node.instance_type, node.zone,
                                        wk.CAPACITY_TYPE_SPOT)
            if rate >= REBALANCE_RATE_THRESHOLD:
                out.append((node, rate))
        return out

    def _safe_offering(self, node):
        """Cheapest offering of the node's instance type with forecast
        rate below the threshold at sticker price ≤ the node's — the
        cost-never-raised guarantee is by construction, not by audit."""
        catalog = self.cloudprovider.catalog_for(None)
        itype = catalog.by_name.get(node.instance_type)
        if itype is None:
            return None, None
        best = None
        for o in itype.offerings:
            if not o.available:
                continue
            if o.zone == node.zone and o.capacity_type == node.capacity_type:
                continue
            if self.forecaster.rate(itype.name, o.zone, o.capacity_type) \
                    >= REBALANCE_RATE_THRESHOLD:
                continue
            if o.price > node.price + 1e-9:
                continue
            key = (o.price, o.capacity_type != wk.CAPACITY_TYPE_SPOT, o.zone)
            if best is None or key < best[0]:
                best = (key, o)
        return (itype, best[1]) if best else (itype, None)

    def _begin_rebalance(self, node, rate: float, now: float) -> bool:
        from ..oracle.scheduler import Option
        from ..solver.core import SolvedNode, SolveResult

        itype, offering = self._safe_offering(node)
        if offering is None:
            _count("spot_rebalance_no_safe_pool")
            self.actions.inc(action="no-safe-pool")
            return False
        prov = next((p for p in self.kube.provisioners()
                     if p.name == node.provisioner_name), None)
        if prov is None or self.provisioning is None:
            return False
        if self.journal is not None:
            # write-ahead BEFORE the launch: the pending state machine
            # otherwise lives only in process memory
            self.journal.record(REBALANCE, node.name, {
                "node": node.name, "replacement": None})
        solved = SolvedNode(
            option=Option(index=-1, itype=itype, zone=offering.zone,
                          capacity_type=offering.capacity_type,
                          price=offering.price,
                          alloc=tuple(itype.allocatable_vector())),
            pod_counts={}, provisioner=prov)
        empty = SolveResult(nodes=[], existing_counts={}, unschedulable={},
                            groups=[])
        try:
            replacement = self.provisioning._launch_node(solved, {}, empty)
        except Exception as e:
            log.warning("rebalance replacement launch failed: %s", e)
            replacement = None
        if replacement is None:
            self._resolve(node.name, "aborted")
            return False
        if self.journal is not None:
            self.journal.record(REBALANCE, node.name, {
                "node": node.name, "replacement": replacement.name})
        crashpoint("spot.mid_rebalance")
        self.limiter.spend(1)
        self.ledger.append({
            "node": node.name,
            "node_pool": [node.instance_type, node.zone, node.capacity_type],
            "node_price": node.price,
            "replacement": replacement.name,
            "replacement_pool": [itype.name, offering.zone,
                                 offering.capacity_type],
            "replacement_price": offering.price,
            "rate": round(rate, 6),
        })
        _count("spot_rebalance_launched")
        self.actions.inc(action="launched")
        self.recorder.normal(
            f"node/{node.name}", "SpotRebalance",
            f"forecast rate {rate:.3f} >= {REBALANCE_RATE_THRESHOLD}; "
            f"launched {replacement.name} "
            f"({itype.name}/{offering.zone}/{offering.capacity_type}); "
            f"draining once initialized")
        self._pending = {"node": node.name, "replacement": replacement.name,
                         "rate": rate, "started_ts": now}
        return True

    def _finish_pending(self, now: float) -> int:
        pr = self._pending
        node = self.cluster.nodes.get(pr["node"])
        rep = self.cluster.nodes.get(pr["replacement"])
        if node is None or node.marked_for_deletion:
            # the platform reclaimed it first (or another path is draining
            # it) — the proactive move is moot; the replacement stays as
            # restored capacity
            self._pending = None
            self._resolve(pr["node"], "overtaken")
            _count("spot_rebalance_overtaken")
            self.actions.inc(action="overtaken")
            return 0
        if rep is None or rep.marked_for_deletion:
            log.warning("rebalance replacement %s gone; abandoning",
                        pr["replacement"])
            self._pending = None
            self._resolve(pr["node"], "abandoned")
            self.actions.inc(action="abandoned")
            return 0
        if rep.initialized:
            self._pending = None
            t0 = time.perf_counter()
            if self.termination is None or \
                    not self.termination.request_deletion(pr["node"]):
                # old node no longer drainable: roll the replacement back
                if self.termination is not None:
                    self.termination.request_deletion(pr["replacement"])
                self._resolve(pr["node"], "rolled_back")
                _count("spot_rebalance_rolled_back")
                self.actions.inc(action="rolled-back")
                return 0
            explain.note_drain(pr["node"], "rebalance",
                               "proactive-rebalance", ts=now,
                               detail={"replacement": pr["replacement"],
                                       "rate": pr["rate"]})
            elapsed = time.perf_counter() - t0
            if elapsed > 0:
                self.drain_throughput.observe(
                    1.0 / elapsed, reason="proactive-rebalance")
            self._resolve(pr["node"], "completed")
            _count("spot_rebalance_drained")
            self.actions.inc(action="drained")
            self.recorder.normal(
                f"node/{pr['node']}", "SpotRebalance",
                f"drained ahead of predicted reclaim "
                f"(rate {pr['rate']:.3f}, reason proactive-rebalance); "
                f"workload lands on {pr['replacement']}")
            return 1
        if now - pr["started_ts"] >= self.REBALANCE_INIT_TIMEOUT_S:
            log.warning("rebalance replacement %s not initialized within "
                        "%.0fs; rolling back", pr["replacement"],
                        self.REBALANCE_INIT_TIMEOUT_S)
            if self.termination is not None:
                self.termination.request_deletion(pr["replacement"])
            self._pending = None
            self._resolve(pr["node"], "rolled_back")
            _count("spot_rebalance_rolled_back")
            self.actions.inc(action="rolled-back")
        return 0

    def _resolve(self, key: str, outcome: str) -> None:
        if self.journal is not None:
            self.journal.resolve(REBALANCE, key, outcome=outcome)

    def snapshot(self) -> dict:
        return {"pending": dict(self._pending) if self._pending else None,
                "limiter": self.limiter.snapshot(),
                "ledger_entries": len(self.ledger),
                "counters": counters()}
