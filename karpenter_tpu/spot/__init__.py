"""Spot-storm resilience plane: forecast, risk-aware solve, proactive drain.

Three cooperating pieces (ISSUE 19):

* :class:`SpotForecaster` (forecaster.py) — per-(instance-type, zone,
  capacity-type) price and interruption-rate estimates behind a
  live → ledger → static DegradeLadder (the pricing degrade chain's
  shape), exposed as ``karpenter_spot_*`` gauges and a statusz section.
* :class:`RiskObjective` (objective.py) — the solve's price vector
  becomes price × interruption penalty, plus an iterative diversity
  floor encoded through the dense-mask "diversity" dimension (kernel
  ``option_mask`` / oracle ``barred``, bit-parity audited). Real sticker
  prices are restored before any result reaches apply.
* :class:`RebalanceController` (rebalance.py) — drains at-risk nodes
  ahead of predicted reclaims through the two-phase replace shape,
  journaled via the recovery plane, rate-limited so churn never exceeds
  the interruption mass it avoids.

Strict-noop contract: with ``KARPENTER_TPU_SPOT=0`` nothing here runs
and no counter in :func:`activity` moves (chaos invariant
``spot-strict-noop``); solve decisions are bit-identical to a build
without the plane.
"""
from __future__ import annotations

from .forecaster import (FORECAST_RUNGS, RATE_CAP, REBALANCE_RATE_THRESHOLD,
                         RISK_WEIGHT, STATIC_RATES, SpotForecaster)
from .objective import (DEFAULT_DIVERSITY_FLOOR, DIVERSITY_FLOOR_ENV,
                        RiskObjective, diversity_floor, diversity_report,
                        diversity_violations, pool_mask, restore_real_prices,
                        risk_adjusted_catalog, spread_transform)
from .rebalance import RebalanceController, RebalanceRateLimiter
from .state import FLAG_ENV, disabled, enabled, set_enabled

from . import forecaster as _forecaster_mod
from . import objective as _objective_mod
from . import rebalance as _rebalance_mod

__all__ = [
    "DEFAULT_DIVERSITY_FLOOR", "DIVERSITY_FLOOR_ENV", "FLAG_ENV",
    "FORECAST_RUNGS", "RATE_CAP", "REBALANCE_RATE_THRESHOLD", "RISK_WEIGHT",
    "RebalanceController", "RebalanceRateLimiter", "RiskObjective",
    "STATIC_RATES", "SpotForecaster", "activity", "disabled",
    "diversity_floor", "diversity_report", "diversity_violations", "enabled",
    "pool_mask", "restore_real_prices", "risk_adjusted_catalog",
    "set_enabled", "spread_transform",
]


def activity() -> "dict[str, int]":
    """Flat monotone counters for the chaos strict-noop diff: every number
    here must stay frozen while the plane is disabled (forecaster refreshes,
    objective solves, rebalance actions)."""
    out: "dict[str, int]" = {}
    out.update(_forecaster_mod.counters())
    out.update(_objective_mod.counters())
    out.update(_rebalance_mod.counters())
    return out
