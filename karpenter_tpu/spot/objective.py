"""Risk-aware solve objective: price × interruption penalty + diversity floor.

Two mechanisms, both advisory and strict-noop under ``KARPENTER_TPU_SPOT=0``:

* **Risk-adjusted prices** — the solve's price vector becomes
  ``price × forecaster.penalty(pool)`` via a cloned catalog whose spot
  offering prices carry the penalty (on-demand penalties are exactly 1.0,
  so those prices are bit-identical). Both solver backends and the scalar
  oracle consume catalogs, so kernel/oracle parity on the adjusted
  objective follows from the existing parity machinery with zero new
  device code. After the solve, :func:`restore_real_prices` maps every
  decision back onto the REAL catalog's options — node records, the price
  column, and the consolidation cost invariant only ever see sticker
  prices (check_consolidation_cost compares real catalog floats).

* **Diversity floor** — no more than ``DIVERSITY_FLOOR`` of a workload's
  newly-placed capacity may land on one spot pool. Enforced in two
  phases. Phase 1 *splits*: the violating workloads get a soft zone
  topology-spread injected (``ScheduleAnyway``), so the shared
  ``prepare_groups`` pre-pass water-fills the group across zones on BOTH
  solver paths — the only mechanism that can break up a single pod group,
  since a whole-solve re-run moves a group in one piece. Phase 2 *bars*
  residual over-concentrated pools through the extra dense-mask dimension
  (encode_problem ``option_mask`` on the kernel path, Scheduler
  ``barred`` on the oracle path — bit-parity enforced by the "diversity"
  MASK_DIMENSIONS entry + clause) and re-solves, bounded by the spot-pool
  count. Both phases are guarded, in precedence order
  never-strands > cost-never-raised > diversity: an attempt that raises
  the unschedulable count above the baseline, or raises the total
  STICKER cost of the placement (real catalog prices, not risk-adjusted
  ones), is rolled back and the concentration accepted — recorded in the
  DecisionRecord either way.

The objective only activates when the forecaster sees ELEVATED risk
(max forecast rate ≥ forecaster.REBALANCE_RATE_THRESHOLD). At the static
baseline every solve is bit-identical to a build without this module —
the advisory plane stays out of the steady-state hot path, and the chaos
``spot-strict-noop`` two-window evidence holds trivially outside storms.
"""

from __future__ import annotations

import dataclasses
import logging
import os
import threading
from typing import Callable, Optional

import numpy as np

from ..apis import wellknown as wk
from ..models.instancetype import Catalog, InstanceType, Offerings
from ..models.pod import TopologySpreadConstraint
from . import state
from .forecaster import REBALANCE_RATE_THRESHOLD, SpotForecaster

log = logging.getLogger("karpenter.spot")

# max fraction of one workload's newly-placed pods on a single spot pool
DIVERSITY_FLOOR_ENV = "KARPENTER_TPU_SPOT_DIVERSITY_FLOOR"
DEFAULT_DIVERSITY_FLOOR = 0.5

_counters_lock = threading.Lock()
_COUNTERS = {
    "spot_objective_solves": 0,
    "spot_objective_resolves": 0,
    "spot_workloads_spread": 0,
    "spot_spreads_rolled_back": 0,
    "spot_pools_barred": 0,
    "spot_bars_rolled_back": 0,
    "spot_assignments_cited": 0,
}


def _count(key: str, n: int = 1) -> None:
    with _counters_lock:
        _COUNTERS[key] += n


def counters() -> "dict[str, int]":
    with _counters_lock:
        return dict(_COUNTERS)


def diversity_floor() -> float:
    try:
        f = float(os.environ.get(DIVERSITY_FLOOR_ENV,
                                 DEFAULT_DIVERSITY_FLOOR))
    except ValueError:
        return DEFAULT_DIVERSITY_FLOOR
    return min(max(f, 0.0), 1.0)


def risk_adjusted_catalog(catalog: Catalog,
                          forecaster: SpotForecaster) -> Catalog:
    """Clone with spot offering prices × penalty (same types, same zones,
    same offering lattice — the grid layout differs only in price floats,
    on-demand rows bit-identical because their penalty is exactly 1.0)."""
    types = []
    for t in catalog.types:
        offerings = Offerings(
            dataclasses.replace(
                o, price=o.price * forecaster.penalty(
                    t.name, o.zone, o.capacity_type))
            for o in t.offerings)
        types.append(dataclasses.replace(t, offerings=offerings))
    return Catalog(types=types, seqnum=catalog.seqnum)


def pool_mask(catalog: Catalog,
              barred: "set[tuple[str, str, str]]") -> np.ndarray:
    """bool [T, S] option mask with the barred (type, zone, capacityType)
    pools False — same axis derivation as models/encode.py build_grid
    (types in catalog order; S = sorted-zone × wk.CAPACITY_TYPES)."""
    zones = sorted({o.zone for t in catalog.types for o in t.offerings})
    cts = list(wk.CAPACITY_TYPES)
    zi_of = {z: i for i, z in enumerate(zones)}
    ci_of = {c: i for i, c in enumerate(cts)}
    mask = np.ones((len(catalog.types), len(zones) * len(cts)), dtype=bool)
    for ti, t in enumerate(catalog.types):
        for name, zone, ct in barred:
            if name != t.name:
                continue
            zi, ci = zi_of.get(zone), ci_of.get(ct)
            if zi is not None and ci is not None:
                mask[ti, zi * len(cts) + ci] = False
    return mask


def diversity_report(result, floor: float
                     ) -> "dict[object, set[tuple[str, str, str]]]":
    """Per-workload over-concentration: origin key -> the spot pools
    holding more than `floor` of that workload's newly placed pods
    (workload = pod-group origin key, the same identity the per-node
    topology caps budget on). A pool is always allowed one pod per
    workload — a 1-pod workload is 100 % concentrated by definition and
    barring would just flap."""
    per_wl: "dict[object, dict[tuple[str, str, str], int]]" = {}
    totals: "dict[object, int]" = {}
    for n in result.nodes:
        pool = (n.option.itype.name, n.option.zone, n.option.capacity_type)
        for g_idx, cnt in n.pod_counts.items():
            okey = result.groups[g_idx].spec.origin_key()
            totals[okey] = totals.get(okey, 0) + cnt
            if n.option.capacity_type == wk.CAPACITY_TYPE_SPOT:
                pools = per_wl.setdefault(okey, {})
                pools[pool] = pools.get(pool, 0) + cnt
    report: "dict[object, set[tuple[str, str, str]]]" = {}
    for okey, pools in per_wl.items():
        tot = totals.get(okey, 0)
        bad = {pool for pool, c in pools.items()
               if c > max(floor * tot, 1.0) + 1e-9}
        if bad:
            report[okey] = bad
    return report


def diversity_violations(result, floor: float) -> "set[tuple[str, str, str]]":
    """The union of over-concentrated spot pools across all workloads."""
    viol: "set[tuple[str, str, str]]" = set()
    for pools in diversity_report(result, floor).values():
        viol |= pools
    return viol


def _sticker_prices(catalog: Catalog) -> "dict[tuple[str, str, str], float]":
    return {(t.name, o.zone, o.capacity_type): o.price
            for t in catalog.types for o in t.offerings}


def _sticker_cost(result, prices: "dict[tuple[str, str, str], float]") -> float:
    """Total REAL hourly cost of a placement — what the diversity guards
    compare. The risk-adjusted prices shape the choice; the invariant the
    storm drill audits (cost-never-raised) is on sticker dollars."""
    total = 0.0
    for n in result.nodes:
        total += prices.get(
            (n.option.itype.name, n.option.zone, n.option.capacity_type),
            n.option.price)
    return total


def spread_transform(keys: "set") -> "Callable[[list], list]":
    """Pod transform injecting a SOFT zone topology-spread on every pod
    whose workload over-concentrated: the shared prepare_groups pre-pass
    (oracle/scheduler.py split_zone_spread, verbatim on the kernel encode
    path) then water-fills the group across zones — the only lever that
    can split a single pod group, since whole-solve re-runs move a group
    in one piece. ScheduleAnyway, so relaxation drops the pin rather than
    strand a pod a zone can't host. Pods that already carry a zone
    topology constraint are left alone (the user's spread wins)."""
    spread = TopologySpreadConstraint(
        max_skew=1, topology_key=wk.LABEL_ZONE,
        when_unsatisfiable="ScheduleAnyway")

    def xform(pods):
        out = []
        for p in pods:
            if p.origin_key() in keys and not any(
                    c.topology_key == wk.LABEL_ZONE for c in p.topology):
                p = dataclasses.replace(p, topology=p.topology + (spread,))
            out.append(p)
        return out
    return xform


def restore_real_prices(result, catalog: Catalog) -> None:
    """Map every solved node's option back onto the REAL catalog (in
    place): the risk penalty shapes the CHOICE, never the recorded price —
    node records, the cluster price column, and the consolidation cost
    invariant all compare sticker prices."""
    for i, n in enumerate(result.nodes):
        real_t = catalog.by_name.get(n.option.itype.name)
        if real_t is None:
            continue
        price = None
        for o in real_t.offerings:
            if o.zone == n.option.zone and \
                    o.capacity_type == n.option.capacity_type:
                price = o.price
                break
        if price is None:
            continue
        result.nodes[i] = dataclasses.replace(
            n, option=dataclasses.replace(
                n.option, itype=real_t, price=price))


class RiskObjective:
    """The risk-aware solve driver provisioning calls when the forecaster
    sees elevated risk. ``solve_fn(catalog, option_mask, barred,
    pod_transform)`` runs one routed solve — the kernel backends consume
    the mask, the oracle fallback the barred pool set (both encode the
    same dimension), and ``pod_transform`` (or None) rewrites the pending
    pod list before grouping (the spread-injection phase rides on it)."""

    def __init__(self, forecaster: SpotForecaster,
                 floor: "Optional[float]" = None):
        self.forecaster = forecaster
        self.floor = diversity_floor() if floor is None else floor
        self._memo: "Optional[tuple]" = None

    def active(self) -> bool:
        if not state.enabled():
            return False
        snap = self.forecaster.snapshot()
        mx = snap.get("max_rate")
        return mx is not None and mx >= REBALANCE_RATE_THRESHOLD

    def adjusted(self, catalog: Catalog) -> Catalog:
        key = (id(catalog), catalog.seqnum,
               tuple(sorted(self.forecaster._rates.items())))
        if self._memo is not None and self._memo[0] == key:
            return self._memo[1]
        adj = risk_adjusted_catalog(catalog, self.forecaster)
        self._memo = (key, adj)
        return adj

    def solve(self, catalog: Catalog,
              solve_fn: "Callable[..., object]") -> "tuple[object, dict]":
        """Risk-adjusted solve + two-phase diversity-floor enforcement
        (spread-split, then cost-guarded pool bars). Returns (result with
        REAL prices restored, info dict for the DecisionRecord/evidence)."""
        adj = self.adjusted(catalog)
        _count("spot_objective_solves")
        prices = _sticker_prices(catalog)
        result = solve_fn(adj, None, None, None)
        baseline_unsched = result.unschedulable_count()
        base_cost = _sticker_cost(result, prices)
        barred: "set[tuple[str, str, str]]" = set()
        accepted_viol: "set[tuple[str, str, str]]" = set()
        spread_names: "list[str]" = []
        xform = None
        # phase 1 — split: a whole-solve re-run cannot break up one pod
        # group (FFD moves it in a piece), so inject a soft zone spread on
        # the over-concentrated workloads and let the shared pre-pass
        # water-fill them across zones on both solver paths
        report = diversity_report(result, self.floor)
        if report:
            keys = set(report)
            names = sorted({g.spec.name for g in result.groups
                            if g.spec.origin_key() in keys})
            cand_xform = spread_transform(keys)
            attempt = solve_fn(adj, None, None, cand_xform)
            _count("spot_objective_resolves")
            if attempt.unschedulable_count() <= baseline_unsched and \
                    _sticker_cost(attempt, prices) <= base_cost + 1e-9:
                result = attempt
                xform = cand_xform
                spread_names = names
                _count("spot_workloads_spread", len(keys))
            else:
                # spreading stranded a pod or cost sticker dollars (zones
                # price spot differently) — fall through to the bar loop
                _count("spot_spreads_rolled_back", len(keys))
        # phase 2 — bar: each round bars at least one new spot pool, so
        # the loop is bounded by the (finite) spot-pool universe
        n_spot_pools = sum(1 for t in adj.types for o in t.offerings
                           if o.capacity_type == wk.CAPACITY_TYPE_SPOT)
        for _ in range(n_spot_pools):
            viol = diversity_violations(result, self.floor) \
                - barred - accepted_viol
            if not viol:
                break
            candidate = barred | viol
            mask = pool_mask(adj, candidate)
            attempt = solve_fn(adj, mask, candidate, xform)
            _count("spot_objective_resolves")
            if attempt.unschedulable_count() > baseline_unsched or \
                    _sticker_cost(attempt, prices) > base_cost + 1e-9:
                # the floor would strand pods or raise real cost — roll
                # the bar back and accept the concentration
                # (never-strands > cost-never-raised > diversity)
                accepted_viol |= viol
                _count("spot_bars_rolled_back", len(viol))
                continue
            barred = candidate
            result = attempt
            _count("spot_pools_barred", len(viol))
        info = self._cite(result, barred, accepted_viol, spread_names)
        restore_real_prices(result, catalog)
        return result, info

    def _cite(self, result, barred, accepted_viol, spread_names) -> dict:
        """DecisionRecord citing the risk term for every spot-influenced
        assignment (ISSUE 19 tentpole contract)."""
        from ..explain import DECISIONS

        rung = self.forecaster.rung()
        cites = []
        for n in result.nodes:
            if n.option.capacity_type != wk.CAPACITY_TYPE_SPOT:
                continue
            rate = self.forecaster.rate(n.option.itype.name, n.option.zone,
                                        n.option.capacity_type)
            cites.append({
                "pool": [n.option.itype.name, n.option.zone, "spot"],
                "pods": n.pod_count,
                "rate": round(rate, 6),
                "penalty": round(self.forecaster.penalty(
                    n.option.itype.name, n.option.zone,
                    n.option.capacity_type), 6),
            })
        _count("spot_assignments_cited", len(cites))
        info = {
            "risk_weight": __import__(
                "karpenter_tpu.spot.forecaster",
                fromlist=["RISK_WEIGHT"]).RISK_WEIGHT,
            "forecast_rung": rung,
            "diversity_floor": self.floor,
            "workloads_spread": spread_names,
            "barred_pools": sorted(list(p) for p in barred),
            "accepted_concentrations": sorted(
                list(p) for p in accepted_viol),
            "spot_assignments": cites[:50],
            "spot_assignments_total": len(cites),
        }
        DECISIONS.emit("spot-objective", info)
        return info
