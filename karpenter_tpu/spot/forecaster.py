"""Spot interruption forecaster: per-pool reclaim-rate estimates.

Extends the pricing provider's view of an offering — (instance type,
zone, capacity type) → $/h — with an *interruption-rate* estimate for the
same key, fed by a live→ledger→static fallback ladder of the exact shape
the pricing live→static chain already uses (resilience.DegradeLadder,
sticky with single-step recovery probes):

  rung 0  live    an injected feed (the cloud's rebalance-recommendation /
                  spot-advisor analogue; the storm drill injects its
                  schedule here, including the adversarial wrong one)
  rung 1  ledger  rates derived deterministically from the committed perf
                  ledger corpus (benchmarks/results/ledger.jsonl) — same
                  seed + same ledger bytes → bit-identical forecasts
  rung 2  static  the embedded per-capacity-type table, always available

The forecast is advisory-never-load-bearing: with the plane disabled
(``KARPENTER_TPU_SPOT=0``, spot.state) every rate is 0.0, every penalty
is exactly 1.0, and no counter or gauge moves — the chaos
``spot-strict-noop`` invariant audits that. On-demand capacity is never
forecast to be reclaimed (rate pinned 0.0), so the risk-adjusted price of
an on-demand offering equals its real price bit-for-bit.

The penalty the risk-aware objective multiplies into the price vector:

    penalty = 1.0 + RISK_WEIGHT * min(rate, RATE_CAP)

i.e. a pool forecast at the 5 %/cycle static baseline costs 10 % extra in
the objective; a pool in a predicted storm (rate ≥ RATE_CAP) costs at
most 1 + RISK_WEIGHT times its sticker price. Bounded and monotone so the
oracle tie-break order stays total.
"""

from __future__ import annotations

import hashlib
import logging
import os
import pathlib
import threading
from typing import Callable, Optional

from ..metrics import NAMESPACE, REGISTRY
from ..resilience.degrade import DegradeLadder
from ..utils.clock import Clock
from . import state

log = logging.getLogger("karpenter.spot")

FORECAST_RUNGS = ("live", "ledger", "static")

# objective shaping knobs (docs/spot.md documents all three)
RISK_WEIGHT = float(os.environ.get("KARPENTER_TPU_SPOT_RISK_WEIGHT", "2.0"))
RATE_CAP = 0.5
# embedded static baseline: interruption probability per reconcile cycle
STATIC_RATES = {"spot": 0.05, "on-demand": 0.0}
# the rebalance controller only acts on pools forecast ABOVE this — the
# static baseline sits below it, so a forecaster running on the static
# rung never triggers proactive churn
REBALANCE_RATE_THRESHOLD = 0.15

_DEFAULT_LEDGER = (pathlib.Path(__file__).resolve().parent.parent.parent
                   / "benchmarks" / "results" / "ledger.jsonl")

_counters_lock = threading.Lock()
_COUNTERS = {
    "spot_forecast_refreshes": 0,
    "spot_forecasts_computed": 0,
    "spot_forecast_ladder_fallbacks": 0,
    "spot_forecast_rung_warnings": 0,
}


def _count(key: str, n: int = 1) -> None:
    with _counters_lock:
        _COUNTERS[key] += n


def counters() -> "dict[str, int]":
    with _counters_lock:
        return dict(_COUNTERS)


def _stable_u01(*parts) -> float:
    """Deterministic [0,1) from a sha256 of the parts — hash() is salted
    per-process (PYTHONHASHSEED) and would break the same-seed+same-ledger
    → identical-forecasts property."""
    h = hashlib.sha256("\x1f".join(str(p) for p in parts).encode())
    return int.from_bytes(h.digest()[:8], "big") / 2**64


class SpotForecaster:
    """Per-(instance type, zone, capacity type) interruption-rate feed.

    ``live_source`` is an optional callable returning
    ``dict[(itype, zone, ct)] -> rate`` (the drill injects schedules
    here); returning ``None``/raising fails the live rung and the ladder
    falls to the ledger corpus, then to the static table.
    """

    def __init__(self, clock: "Optional[Clock]" = None, recorder=None,
                 registry=None, seed: int = 0,
                 ledger_path: "Optional[str]" = None,
                 live_source: "Optional[Callable[[], Optional[dict]]]" = None):
        self.clock = clock or Clock()
        self.seed = int(seed)
        self.live_source = live_source
        self._recorder = recorder
        self._registry = registry
        self.ledger_path = pathlib.Path(
            ledger_path or os.environ.get("KARPENTER_TPU_LEDGER",
                                          str(_DEFAULT_LEDGER)))
        self.ladder = DegradeLadder(
            "spot.forecast", FORECAST_RUNGS, clock=self.clock,
            recorder=recorder, registry=registry)
        reg = registry if registry is not None else REGISTRY
        self._rate_gauge = reg.gauge(
            f"{NAMESPACE}_spot_interruption_rate",
            "Forecast interruption probability per cycle, per spot pool.",
            ("instance_type", "zone"))
        self._rung_gauge = reg.gauge(
            f"{NAMESPACE}_spot_forecast_rung",
            "Fallback-ladder rung the current forecast came from "
            "(0=live 1=ledger 2=static).")
        self._lock = threading.Lock()
        self._rates: "dict[tuple[str, str, str], float]" = {}
        self._rung: "Optional[int]" = None
        self._last_refresh: "Optional[float]" = None

    # -- the fallback ladder -----------------------------------------------------

    def set_live_source(self, source: "Optional[Callable]") -> None:
        """Swap the live feed and re-arm the ladder at the live rung. The
        ladder's single-step recovery probes exist to keep a *flapping*
        dependency from yanking the chain around; a *replaced* feed (config
        reload, drill injection) carries no such history, so the next
        refresh() tries it immediately."""
        self.live_source = source
        self.ladder = DegradeLadder(
            "spot.forecast", FORECAST_RUNGS, clock=self.clock,
            recorder=self._recorder, registry=self._registry)

    def refresh(self) -> "Optional[int]":
        """One forecast refresh down the ladder; returns the rung that
        served it (None while the plane is disabled — strict noop)."""
        if not state.enabled():
            return None
        start = self.ladder.start_rung()
        for rung in range(start, len(FORECAST_RUNGS)):
            try:
                rates = self._source(rung)
            except Exception as e:  # noqa: BLE001 — fall down the ladder
                log.warning("spot forecast rung %s failed: %s",
                            FORECAST_RUNGS[rung], e)
                rates = None
            if rates is None:
                self.ladder.record_failure(rung)
                _count("spot_forecast_ladder_fallbacks")
                continue
            self.ladder.record_success(rung)
            with self._lock:
                prev_rung = self._rung
                self._rates = dict(rates)
                self._rung = rung
                self._last_refresh = self.clock.now()
            _count("spot_forecast_refreshes")
            self._rung_gauge.set(rung)
            for (itype, zone, ct), r in rates.items():
                if ct == "spot":
                    self._rate_gauge.set(round(r, 6), instance_type=itype,
                                         zone=zone)
            if rung > 0 and rung != prev_rung:
                # satellite contract: a forecaster entering a degraded rung
                # says so out loud — once per transition, not per refresh
                # (the runbook greps for this line)
                log.warning(
                    "spot forecaster running on %s rung (live feed "
                    "unavailable); rates are %s estimates",
                    FORECAST_RUNGS[rung],
                    "ledger-derived" if rung == 1 else "static baseline")
                _count("spot_forecast_rung_warnings")
            return rung
        return None  # unreachable: the static rung never fails

    def _source(self, rung: int) -> "Optional[dict]":
        if rung == 0:
            return self.live_source() if self.live_source is not None else None
        if rung == 1:
            return self._ledger_rates()
        return {}  # static: rate() falls back to STATIC_RATES per lookup

    def _ledger_rates(self) -> "Optional[dict]":
        """Deterministic fleet-wide spot rate from the committed ledger
        corpus: the static baseline modulated by a stable jitter keyed on
        (seed, sha256 of the ledger bytes). The corpus carries no per-pool
        signal, so the rung publishes one wildcard rate — still strictly
        better than the static table because it moves with the committed
        evidence, and same seed + same ledger → bit-identical forecasts
        (tests/test_spot.py property test)."""
        try:
            raw = self.ledger_path.read_bytes()
        except OSError:
            return None
        if not any(ln.strip() for ln in raw.splitlines()):
            return None
        digest = hashlib.sha256(raw).hexdigest()
        jitter = _stable_u01(self.seed, digest)
        return {("*", "*", "spot"): round(
            STATIC_RATES["spot"] * (0.5 + jitter), 6)}

    # -- the advisory surface ----------------------------------------------------

    def rate(self, instance_type: str, zone: str, capacity_type: str) -> float:
        """Forecast interruption probability per cycle for one offering.
        0.0 for on-demand always; 0.0 for everything while disabled."""
        if not state.enabled():
            return 0.0
        if capacity_type != "spot":
            return 0.0
        _count("spot_forecasts_computed")
        with self._lock:
            r = self._rates.get((instance_type, zone, capacity_type))
            if r is None:  # ledger rung publishes one fleet-wide rate
                r = self._rates.get(("*", "*", capacity_type))
        if r is None:
            r = STATIC_RATES.get(capacity_type, 0.0)
        return min(max(r, 0.0), 1.0)

    def penalty(self, instance_type: str, zone: str,
                capacity_type: str) -> float:
        """The multiplicative risk term the objective applies to price.
        Exactly 1.0 for on-demand and whenever the plane is disabled."""
        if not state.enabled():
            return 1.0
        r = self.rate(instance_type, zone, capacity_type)
        if r <= 0.0:
            return 1.0
        return 1.0 + RISK_WEIGHT * min(r, RATE_CAP)

    # -- observability -----------------------------------------------------------

    def rung(self) -> "Optional[int]":
        with self._lock:
            return self._rung

    def snapshot(self) -> dict:
        with self._lock:
            rates = dict(self._rates)
            rung = self._rung
            last = self._last_refresh
        return {
            "enabled": state.enabled(),
            "rung": None if rung is None else FORECAST_RUNGS[rung],
            "risk_weight": RISK_WEIGHT,
            "rate_cap": RATE_CAP,
            "rebalance_rate_threshold": REBALANCE_RATE_THRESHOLD,
            "pools": len(rates),
            "max_rate": (round(max(rates.values()), 6) if rates else None),
            "last_refresh_age_s": (None if last is None
                                   else round(self.clock.now() - last, 3)),
            "ladder": self.ladder.snapshot(),
            "counters": counters(),
        }
