"""Model <-> manifest JSON round-trips for every stored kind.

Two regimes, one per object ownership:

- **User-authored kinds** (pods, provisioners, nodetemplates, pdbs): read
  real Kubernetes manifests via apis.yaml_compat (the same parser the
  examples/replay harness uses), so objects applied by kubectl work
  unchanged. Objects written by THIS framework additionally embed their
  exact model (`x-karpenter-model`) so round-trips are lossless — k8s
  schema can't express every internal field bit-for-bit.
- **Controller-owned kinds** (machines, nodes, leases, configmaps): these
  are our CRDs; the manifest schema is the embedded model itself.

Reference analogue: the reference's CRD types ARE its Go structs with
k8s codegen (/root/reference/pkg/apis/v1alpha1); here the generic tagged
encoder plays the codegen role.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

from ..apis.nodetemplate import (BlockDeviceMapping, MetadataOptions,
                                 NodeTemplate, NodeTemplateStatus)
from ..apis.provisioner import KubeletConfiguration, Limits, Provisioner
from ..models.cluster import PodDisruptionBudget, StateNode
from ..models.machine import Machine, MachineSpec, MachineStatus
from ..models.pod import (PodAffinityTerm, PodSpec, Taint, Toleration,
                          TopologySpreadConstraint)  # noqa: F401 (Taint used in node parse)
from ..models.requirements import Requirement, Requirements

MODEL_KEY = "x-karpenter-model"

# kind -> (apiVersion, Kind, namespaced)
ROUTES = {
    "pods": ("v1", "Pod", True),
    "nodes": ("v1", "Node", False),
    "configmaps": ("v1", "ConfigMap", True),
    "pdbs": ("policy/v1", "PodDisruptionBudget", True),
    "leases": ("coordination.k8s.io/v1", "Lease", True),
    "provisioners": ("karpenter.sh/v1alpha5", "Provisioner", False),
    "machines": ("karpenter.sh/v1alpha5", "Machine", False),
    "nodetemplates": ("karpenter.k8s.tpu/v1alpha1", "NodeTemplate", False),
    "events": ("v1", "Event", True),
    "intents": ("karpenter.sh/v1alpha5", "Intent", False),
}

# registered dataclasses for the tagged generic encoder
_TYPES = {}
for _cls in (PodSpec, Taint, Toleration, TopologySpreadConstraint,
             PodAffinityTerm, Machine, MachineSpec, MachineStatus, StateNode,
             Provisioner, Limits, KubeletConfiguration, NodeTemplate,
             NodeTemplateStatus, MetadataOptions, BlockDeviceMapping,
             PodDisruptionBudget):
    _TYPES[_cls.__name__] = _cls

# runtime-only fields never serialized (decode restores the default)
_SKIP_FIELDS = {("StateNode", "pods")}


def _register_lease():
    from ..leaderelection import Lease

    _TYPES.setdefault("Lease", Lease)
    return Lease


def _register_intent():
    from ..recovery.journal import IntentRecord

    _TYPES.setdefault("IntentRecord", IntentRecord)
    return IntentRecord


def encode(obj):
    """Model object -> JSON-able value (tagged for exact decode)."""
    if dataclasses.is_dataclass(obj) and not isinstance(obj, type):
        cls_name = type(obj).__name__
        if cls_name == "Lease":
            _register_lease()
        out = {"__dc__": cls_name}
        for f in dataclasses.fields(obj):
            if (cls_name, f.name) in _SKIP_FIELDS:
                continue
            out[f.name] = encode(getattr(obj, f.name))
        return out
    if isinstance(obj, Requirements):
        return {"__requirements__": [
            {"key": k, "op": op, "values": list(v)}
            for k, op, v in obj.to_specs()]}
    if isinstance(obj, tuple):
        return {"__tuple__": [encode(v) for v in obj]}
    if isinstance(obj, dict):
        return {str(k): encode(v) for k, v in obj.items()}
    if isinstance(obj, list):
        return [encode(v) for v in obj]
    return obj  # str/int/float/bool/None


def decode(val):
    if isinstance(val, dict):
        if "__dc__" in val:
            name = val["__dc__"]
            if name == "Lease":
                _register_lease()
            elif name == "IntentRecord":
                _register_intent()
            cls = _TYPES[name]
            kwargs = {k: decode(v) for k, v in val.items() if k != "__dc__"}
            return cls(**kwargs)
        if "__requirements__" in val:
            r = Requirements()
            for spec in val["__requirements__"]:
                r.add(Requirement.create(spec["key"], spec["op"],
                                         spec["values"]))
            return r
        if "__tuple__" in val:
            return tuple(decode(v) for v in val["__tuple__"])
        return {k: decode(v) for k, v in val.items()}
    if isinstance(val, list):
        return [decode(v) for v in val]
    return val


def to_manifest(kind: str, name: str, obj) -> dict:
    """Model -> k8s-shaped manifest (with the exact model embedded)."""
    api_version, k8s_kind, _ = ROUTES[kind]
    doc = {
        "apiVersion": api_version,
        "kind": k8s_kind,
        "metadata": {"name": name},
    }
    if kind == "configmaps":
        doc["data"] = dict(obj.get("data", obj)) if isinstance(obj, dict) \
            else dict(obj)
        return doc
    if kind == "events" and isinstance(obj, dict):
        # native v1 Event fields: a real apiserver prunes unknown fields on
        # built-in types, so kubectl-get-events parity needs the real schema
        # (the embedded model below keeps exact round-trips on our side)
        import datetime

        ref_kind, _, ref_name = str(obj.get("object_ref", "")).partition("/")
        ts = obj.get("ts") or 0.0
        stamp = datetime.datetime.fromtimestamp(
            ts, tz=datetime.timezone.utc).strftime("%Y-%m-%dT%H:%M:%SZ") \
            if ts else None
        doc.update({
            "type": obj.get("kind", "Normal"),
            "reason": obj.get("reason", ""),
            "message": obj.get("message", ""),
            "involvedObject": {"kind": ref_kind.capitalize(),
                               "name": ref_name},
            "source": {"component": "karpenter-tpu"},
        })
        if stamp:
            doc["lastTimestamp"] = stamp
    if kind == "leases" and type(obj).__name__ == "Lease":
        # native coordination.k8s.io/v1 spec: a real apiserver prunes the
        # embedded-model field on built-in types, and a lease that reads back
        # empty looks permanently expired — two controllers would both elect
        # themselves (HA safety). RFC3339 MicroTime like client-go writes.
        import datetime

        def _stamp(ts: float) -> "Optional[str]":
            if not ts:
                return None
            return datetime.datetime.fromtimestamp(
                ts, tz=datetime.timezone.utc).strftime(
                    "%Y-%m-%dT%H:%M:%S.%fZ")

        spec = {
            "holderIdentity": obj.holder,
            "leaseDurationSeconds": int(obj.duration_s),
        }
        if _stamp(obj.acquired_ts):
            spec["acquireTime"] = _stamp(obj.acquired_ts)
        if _stamp(obj.renew_ts):
            spec["renewTime"] = _stamp(obj.renew_ts)
        doc["spec"] = spec
    if kind == "pods" and isinstance(obj, PodSpec):
        # surface the schedulable basics in real schema; exact model embedded
        doc["metadata"]["labels"] = dict(obj.labels)
        doc["spec"] = {"nodeName": obj.node_name} if obj.node_name else {}
    if kind == "nodes" and isinstance(obj, StateNode):
        doc["metadata"]["labels"] = dict(obj.labels)
        if obj.annotations:
            doc["metadata"]["annotations"] = dict(obj.annotations)
        doc["spec"] = {"providerID": obj.provider_id}
        if obj.marked_for_deletion:
            # server-side cordon: a real kube-scheduler must stop
            # targeting a draining node (designs/termination.md step 1)
            doc["spec"]["unschedulable"] = True
    if kind == "machines" and isinstance(obj, Machine):
        # real-schema status for kubectl UX: the machines CRD's printer
        # columns read .status.providerID/.status.phase (deploy/crds);
        # the exact model stays embedded (CRD root preserves unknowns)
        doc["metadata"]["labels"] = dict(obj.labels)
        doc["spec"] = {
            "provisionerName": obj.spec.provisioner_name,
            "machineTemplateRef": obj.spec.machine_template_ref,
        }
        doc["status"] = {
            "providerID": obj.status.provider_id,
            "phase": obj.status.state,
            "instanceType": obj.status.instance_type,
            "zone": obj.status.zone,
            "capacityType": obj.status.capacity_type,
            "nodeName": obj.status.node_name,
        }
    if kind == "nodetemplates" and isinstance(obj, NodeTemplate):
        # real-schema spec+status: the nodetemplate controller PUTs whole
        # objects for status; a spec-less write against a pruning apiserver
        # must not blank the user's kubectl-visible configuration
        doc["spec"] = _nodetemplate_spec(obj)
        if obj.status.subnets or obj.status.security_groups:
            doc["status"] = {
                "subnets": [dict(s) for s in obj.status.subnets],
                "securityGroups": list(obj.status.security_groups),
            }
    if kind == "provisioners" and isinstance(obj, Provisioner):
        # REAL-schema spec, not just the embedded model: the counters
        # controller PUTs whole provisioner objects, and against an
        # apiserver that prunes unknown fields a spec-less write would
        # destroy the user's configuration (the CRD also preserves unknown
        # fields at the root for the embedding, but real-schema fidelity is
        # what kubectl users read back)
        doc["spec"] = _provisioner_spec(obj)
        if obj.status_resources:
            # counters-controller consumption (kubectl-visible)
            doc["status"] = {"resources": dict(obj.status_resources)}
    doc[MODEL_KEY] = encode(obj)
    return doc


def _fmt_bytes(n: int) -> str:
    """Exact k8s quantity: Mi only when lossless, else plain bytes — a
    floor-divided Mi would silently shrink non-Mi-multiple user values on
    the pruning-apiserver round trip."""
    if n % 2**20 == 0:
        return f"{n // 2**20}Mi"
    return str(n)


def _provisioner_spec(p: Provisioner) -> dict:
    """Inverse of yaml_compat._provisioner: the REAL v1alpha5 spec schema.
    Round-trip property: _provisioner(to_manifest(p)) == p up to
    set_defaults (tested in test_httpkube serde suite)."""
    def req_items(reqs: Requirements) -> "list[dict]":
        # to_specs() is THE canonical serializer (merged Exists∩NotIn emits
        # the NotIn+Exists pair, In [] stays match-nothing, bounds fold) —
        # re-implementing it here is how presence/emptiness semantics get
        # silently dropped on the pruning-apiserver path
        items = []
        for key, op, values in reqs.to_specs():
            item = {"key": key, "operator": op}
            if values or op in ("In", "NotIn"):
                item["values"] = list(values)
            items.append(item)
        return items

    def taint_items(taints) -> "list[dict]":
        return [{"key": t.key, **({"value": t.value} if t.value else {}),
                 "effect": t.effect} for t in taints]

    spec: dict = {"requirements": req_items(p.requirements)}
    if p.taints:
        spec["taints"] = taint_items(p.taints)
    if p.startup_taints:
        spec["startupTaints"] = taint_items(p.startup_taints)
    if p.labels:
        spec["labels"] = dict(p.labels)
    if p.annotations:
        spec["annotations"] = dict(p.annotations)
    limits = {}
    if p.limits.cpu_millis is not None:
        limits["cpu"] = f"{p.limits.cpu_millis}m"
    if p.limits.memory_bytes is not None:
        limits["memory"] = _fmt_bytes(p.limits.memory_bytes)
    if limits:
        spec["limits"] = {"resources": limits}
    if p.weight:
        spec["weight"] = p.weight
    if p.ttl_seconds_after_empty is not None:
        spec["ttlSecondsAfterEmpty"] = p.ttl_seconds_after_empty
    if p.ttl_seconds_until_expired is not None:
        spec["ttlSecondsUntilExpired"] = p.ttl_seconds_until_expired
    if p.consolidation_enabled:
        spec["consolidation"] = {"enabled": True}
    k = p.kubelet
    kube: dict = {}
    if k.max_pods is not None:
        kube["maxPods"] = k.max_pods
    if k.pods_per_core is not None:
        kube["podsPerCore"] = k.pods_per_core
    if k.system_reserved_cpu_millis or k.system_reserved_memory_bytes:
        kube["systemReserved"] = {
            **({"cpu": f"{k.system_reserved_cpu_millis}m"}
               if k.system_reserved_cpu_millis else {}),
            **({"memory": _fmt_bytes(k.system_reserved_memory_bytes)}
               if k.system_reserved_memory_bytes else {}),
        }
    if k.kube_reserved_cpu_millis is not None or \
            k.kube_reserved_memory_bytes is not None:
        kube["kubeReserved"] = {
            **({"cpu": f"{k.kube_reserved_cpu_millis}m"}
               if k.kube_reserved_cpu_millis is not None else {}),
            **({"memory": _fmt_bytes(k.kube_reserved_memory_bytes)}
               if k.kube_reserved_memory_bytes is not None else {}),
        }
    if k.eviction_hard_memory_bytes != 100 * 2**20:
        kube["evictionHard"] = {
            "memory.available": _fmt_bytes(k.eviction_hard_memory_bytes)}
    # bootstrap passthrough keys survive the store round trip verbatim
    if k.cluster_dns:
        kube["clusterDNS"] = list(k.cluster_dns)
    if k.container_runtime is not None:
        kube["containerRuntime"] = k.container_runtime
    if k.cpu_cfs_quota is not None:
        kube["cpuCFSQuota"] = k.cpu_cfs_quota
    if k.eviction_soft:
        kube["evictionSoft"] = dict(k.eviction_soft)
    if k.eviction_soft_grace_period:
        kube["evictionSoftGracePeriod"] = dict(k.eviction_soft_grace_period)
    if k.eviction_max_pod_grace_period is not None:
        kube["evictionMaxPodGracePeriod"] = k.eviction_max_pod_grace_period
    if k.image_gc_high_threshold_percent is not None:
        kube["imageGCHighThresholdPercent"] = k.image_gc_high_threshold_percent
    if k.image_gc_low_threshold_percent is not None:
        kube["imageGCLowThresholdPercent"] = k.image_gc_low_threshold_percent
    if kube:
        spec["kubeletConfiguration"] = kube
    if p.provider_ref:
        spec["providerRef"] = {"name": p.provider_ref}
    return spec


def _nodetemplate_spec(t: NodeTemplate) -> dict:
    """Inverse of yaml_compat._nodetemplate (native family/volume names —
    the parser maps both the reference's flavor and ours)."""
    spec: dict = {"amiFamily": t.image_family}
    if t.instance_profile:
        spec["instanceProfile"] = t.instance_profile
    if t.subnet_selector:
        spec["subnetSelector"] = dict(t.subnet_selector)
    if t.security_group_selector:
        spec["securityGroupSelector"] = dict(t.security_group_selector)
    if t.image_selector:
        spec["amiSelector"] = dict(t.image_selector)
    if t.userdata:
        spec["userData"] = t.userdata
    if t.tags:
        spec["tags"] = dict(t.tags)
    if t.launch_template_name:
        spec["launchTemplate"] = t.launch_template_name
    if t.fleet_context:
        spec["context"] = t.fleet_context
    md = t.metadata_options
    if not md.is_default():  # ALL fields, not a hand-picked subset
        spec["metadataOptions"] = {
            "httpEndpoint": md.http_endpoint,
            "httpTokens": md.http_tokens,
            "httpPutResponseHopLimit": md.http_put_response_hop_limit,
            "httpProtocolIPv6": md.http_protocol_ipv6,
        }
    if t.block_device_mappings:
        spec["blockDeviceMappings"] = [
            {"deviceName": b.device_name,
             "ebs": {"volumeSize": f"{b.volume_size_gib}Gi",
                     "volumeType": b.volume_type,
                     "encrypted": b.encrypted,
                     **({"iops": b.iops} if b.iops else {})}}
            for b in t.block_device_mappings]
    if t.detailed_monitoring:
        spec["detailedMonitoring"] = True
    return spec


def from_manifest(kind: str, doc: dict):
    """Manifest -> model. Embedded model wins (lossless); otherwise parse
    the real k8s schema via yaml_compat (kubectl-authored objects)."""
    if kind == "configmaps":
        return {"data": dict(doc.get("data", {}))}
    embedded = doc.get(MODEL_KEY)
    if embedded is not None:
        obj = decode(embedded)
        if kind == "pods":
            # the binding subresource mutates spec.nodeName server-side;
            # the manifest is authoritative over the embedded copy
            node_name = (doc.get("spec") or {}).get("nodeName", "")
            if node_name != obj.node_name:
                obj = dataclasses.replace(obj, node_name=node_name)
        if kind == "nodes":
            # cordon/uncordon PATCH spec.unschedulable without rewriting
            # the embedded model — the server spec is authoritative, else
            # the watch echo would revert the cordon in every peer's cache
            obj.marked_for_deletion = bool(
                (doc.get("spec") or {}).get("unschedulable", False))
            # kubectl-annotated vetoes (do-not-consolidate) PATCH metadata,
            # not the model: server metadata is authoritative too
            obj.annotations = dict(
                (doc.get("metadata") or {}).get("annotations") or {})
        return obj
    return _parse_k8s(kind, doc)


def _parse_k8s(kind: str, doc: dict):
    from ..apis import yaml_compat as yc

    if kind == "pods":
        pod = yc._pod(doc.get("metadata", {}), doc.get("spec", {}))
        node_name = (doc.get("spec") or {}).get("nodeName", "")
        if node_name:
            pod = dataclasses.replace(pod, node_name=node_name)
        return pod
    if kind == "nodetemplates":
        t = yc._nodetemplate(doc)
        st = doc.get("status") or {}
        if st.get("subnets") or st.get("securityGroups"):
            t.status = NodeTemplateStatus(
                subnets=[dict(s) for s in st.get("subnets") or []],
                security_groups=list(st.get("securityGroups") or []),
            )
        return t
    if kind == "provisioners":
        p = yc._provisioner(doc)
        res = (doc.get("status") or {}).get("resources")
        if res:
            p.status_resources = {k: str(v) for k, v in res.items()}
        return p
    if kind == "pdbs":
        return yc._pdb(doc, [doc])
    if kind == "nodes":
        return _parse_k8s_node(doc)
    if kind == "leases":
        return _parse_k8s_lease(doc)
    if kind == "events":
        # other components' events (kubelet, scheduler): normalize to the
        # recorder's stored-dict shape so event listings stay uniform
        import datetime

        ref = doc.get("involvedObject") or {}
        ts = 0.0
        for field in ("lastTimestamp", "eventTime", "firstTimestamp"):
            raw = doc.get(field)
            if raw:
                try:
                    ts = datetime.datetime.fromisoformat(
                        str(raw).replace("Z", "+00:00")).timestamp()
                    break
                except ValueError:
                    continue
        out = {"ts": ts, "kind": doc.get("type", "Normal"),
               "reason": doc.get("reason", ""),
               "object_ref": f"{ref.get('kind', '').lower()}/"
                             f"{ref.get('name', '')}",
               "message": doc.get("message", "")}
        # keep the store name: a pruning apiserver strips the embedded model
        # from our own evt-* events, and the restart prune sweep
        # (Operator._prune_stored_events) can only delete what it can name
        name = (doc.get("metadata") or {}).get("name")
        if name:
            out["name"] = name
        return out
    # foreign object of a controller-owned kind (e.g. a Machine authored by
    # another tool): not ours to interpret — callers skip None
    return None


def _parse_k8s_node(doc: dict) -> StateNode:
    """Kubelet-authored Node manifest -> StateNode (a real cluster has
    pre-existing nodes the informer must not choke on). Best-effort: the
    machine-hydration controller fills in karpenter ownership later."""
    from ..apis import wellknown as wk
    from ..utils.quantity import cpu_millis, mem_bytes

    meta = doc.get("metadata") or {}
    spec = doc.get("spec") or {}
    status = doc.get("status") or {}
    alloc_q = status.get("allocatable") or status.get("capacity") or {}
    caps: "dict[str, int]" = {}
    for key, val in alloc_q.items():
        try:
            if key == "cpu":
                caps[wk.RESOURCE_CPU] = cpu_millis(str(val))
            elif key == "memory":
                caps[wk.RESOURCE_MEMORY] = mem_bytes(str(val))
            elif key == "pods":
                caps[wk.RESOURCE_PODS] = int(val)
        except (ValueError, TypeError):
            continue
    labels = dict(meta.get("labels") or {})
    taints = tuple(
        Taint(key=t.get("key", ""), value=str(t.get("value", "")),
              effect=t.get("effect", ""))
        for t in spec.get("taints") or ())
    return StateNode(
        name=meta.get("name", ""), labels=labels,
        annotations=dict(meta.get("annotations") or {}),
        marked_for_deletion=bool(spec.get("unschedulable", False)),
        allocatable=wk.capacity_vector(caps),
        provider_id=spec.get("providerID", ""),
        instance_type=labels.get(wk.LABEL_INSTANCE_TYPE, ""),
        zone=labels.get(wk.LABEL_ZONE, ""),
        capacity_type=labels.get(wk.LABEL_CAPACITY_TYPE, ""),
        provisioner_name=labels.get(wk.LABEL_PROVISIONER, ""),
        taints=taints)


def _parse_k8s_lease(doc: dict):
    """coordination.k8s.io/v1 Lease manifest -> Lease model (RFC3339
    renewTime -> epoch seconds)."""
    import datetime

    Lease = _register_lease()
    spec = doc.get("spec") or {}

    def ts(key: str) -> float:
        raw = spec.get(key)
        if not raw:
            return 0.0
        try:
            return datetime.datetime.fromisoformat(
                str(raw).replace("Z", "+00:00")).timestamp()
        except ValueError:
            return 0.0

    return Lease(holder=spec.get("holderIdentity", ""),
                 acquired_ts=ts("acquireTime"), renew_ts=ts("renewTime"),
                 duration_s=float(spec.get("leaseDurationSeconds", 15)))


def manifest_name(doc: dict) -> "Optional[str]":
    return (doc.get("metadata") or {}).get("name")
