"""Coordination plane: the protocol every store speaks, plus backends.

- protocol.py — the formal CoordinationPlane surface (implicit since round 1
  in fake/kube.KubeStore, now a checked contract);
- serde.py — model <-> manifest JSON round-trips for every stored kind;
- httpkube.py — HttpKubeStore, a kubernetes-REST client (stdlib HTTP,
  list+watch informer cache) implementing the protocol against a real
  apiserver or the in-repo mini apiserver (fake/apiserver.py, the
  kwok-analogue test infrastructure).
"""

from .protocol import CoordinationPlane  # noqa: F401
