"""The coordination-plane contract (KubeStore protocol, formalized).

Every controller in this framework talks to the cluster through exactly this
surface. Two implementations exist:

- `fake.kube.KubeStore` — in-process store (hermetic tests; the reference's
  envtest analogue);
- `coordination.httpkube.HttpKubeStore` — kubernetes REST client over a real
  apiserver (or the in-repo mini apiserver).

Parity target: the reference boots controller-runtime against a live
apiserver (/root/reference/cmd/controller/main.go:33-65); its unit tier
swaps in envtest. The split here is identical, with this Protocol as the
seam.
"""

from __future__ import annotations

from typing import Callable, Protocol, runtime_checkable


@runtime_checkable
class CoordinationPlane(Protocol):
    """get/create/update/delete/list + watch + typed reads + subresources.

    Semantics every implementation must honor:
    - `create` raises fake.kube.Conflict when the name exists;
    - `compare_and_swap` is atomic on object identity (in-process) or
      resourceVersion (HTTP) and raises Conflict for the loser;
    - `watch` callbacks fire as fn(kind, action in {added, modified,
      deleted}, obj) after the store mutates; `unwatch` deregisters;
    - admission (set_admission) runs before create/update/compare_and_swap
      writes are applied;
    - typed reads (pending_pods, provisioners, ...) reflect every write this
      process has successfully completed (read-your-writes).
    """

    # generic CRUD
    def get(self, kind: str, name: str): ...

    def create(self, kind: str, name: str, obj) -> None: ...

    def update(self, kind: str, name: str, obj) -> None: ...

    def delete(self, kind: str, name: str): ...

    def list(self, kind: str) -> list: ...

    def compare_and_swap(self, kind: str, name: str, expect, obj) -> None: ...

    def delete_if(self, kind: str, name: str, expect) -> bool: ...

    # watch plumbing
    def watch(self, fn: Callable[[str, str, object], None]) -> None: ...

    def unwatch(self, fn: Callable[[str, str, object], None]) -> None: ...

    # admission boundary
    def set_admission(self, fn) -> None: ...

    # typed reads
    def pods(self) -> list: ...

    def pending_pods(self) -> list: ...

    def daemon_pods(self) -> list: ...

    def nodes(self) -> list: ...

    def machines(self) -> list: ...

    def provisioners(self) -> list: ...

    def nodetemplates(self) -> list: ...

    def pdbs(self) -> list: ...

    # subresources
    def bind_pod(self, pod_name: str, node_name: str) -> None: ...
