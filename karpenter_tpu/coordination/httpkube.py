"""HttpKubeStore: the CoordinationPlane over the Kubernetes REST API.

The controller half finally runs against a REAL coordination plane
(VERDICT r2 ask #3; reference boots against a live apiserver,
/root/reference/cmd/controller/main.go:33-65): stdlib-HTTP CRUD + chunked
``?watch=true`` streams — no kubernetes client dependency.

Design: an informer-style client. An inner in-process KubeStore acts as the
local cache; LIST seeds it, one watch thread per kind keeps it current, and
every typed read (pending_pods, provisioners, ...) is served from the cache
exactly like client-go informers serve controllers. Writes go HTTP-first,
then apply to the cache synchronously (read-your-writes); the later watch
echo deduplicates by resourceVersion.

Admission runs client-side before writes (the framework's webhook pipeline
sits at this boundary in-process; a production deployment would register the
same pipeline as real admission webhooks — deploy/karpenter-tpu/).
"""

from __future__ import annotations

import http.client
import json
import logging
import socket
import ssl
import threading
import time
import urllib.error
import urllib.parse
import urllib.request
from typing import Callable, Optional

from ..fake.kube import Conflict, Fenced, KubeStore
from ..metrics import NAMESPACE, REGISTRY
from . import serde

log = logging.getLogger("karpenter.httpkube")

# kind -> (path prefix, plural, namespaced)
_API = {
    "pods": ("/api/v1", "pods", True),
    "nodes": ("/api/v1", "nodes", False),
    "configmaps": ("/api/v1", "configmaps", True),
    "pdbs": ("/apis/policy/v1", "poddisruptionbudgets", True),
    "leases": ("/apis/coordination.k8s.io/v1", "leases", True),
    "provisioners": ("/apis/karpenter.sh/v1alpha5", "provisioners", False),
    "machines": ("/apis/karpenter.sh/v1alpha5", "machines", False),
    "nodetemplates": ("/apis/karpenter.k8s.tpu/v1alpha1", "nodetemplates", False),
    "events": ("/api/v1", "events", True),
    "intents": ("/apis/karpenter.sh/v1alpha5", "intents", False),
}


class ApiError(RuntimeError):
    def __init__(self, code: int, message: str,
                 retry_after: "Optional[float]" = None):
        super().__init__(f"HTTP {code}: {message}")
        self.code = code
        # server-directed backoff (429 Retry-After header, seconds); the
        # client has already honored it through the retry policy's clamped
        # sleep by the time this propagates — the attribute lets callers
        # see what was asked
        self.retry_after = retry_after


def _retry_after_seconds(raw: "Optional[str]") -> "Optional[float]":
    """Parse a Retry-After header's delta-seconds form (the HTTP-date
    form is ignored — an apiserver throttle always sends seconds)."""
    if raw is None:
        return None
    try:
        seconds = float(raw.strip())
    except (ValueError, AttributeError):
        return None
    return seconds if seconds >= 0 else None


def load_kubeconfig(path: str) -> "tuple[str, Optional[str], object]":
    """(server, bearer token, ssl_context_or_None) from a kubeconfig.

    Supports the standard auth shapes: bearer token, cluster CA via
    certificate-authority(-data), client certs via
    client-certificate(-data)/client-key(-data), and
    insecure-skip-tls-verify."""
    import base64
    import os
    import tempfile

    import yaml

    with open(path) as f:
        cfg = yaml.safe_load(f)
    ctx_name = cfg.get("current-context") or cfg["contexts"][0]["name"]
    ctx = next(c["context"] for c in cfg["contexts"] if c["name"] == ctx_name)
    cluster = next(c["cluster"] for c in cfg["clusters"]
                   if c["name"] == ctx["cluster"])
    user = next((u["user"] for u in cfg.get("users", [])
                 if u["name"] == ctx.get("user")), {})
    server = cluster["server"]
    ssl_ctx = None
    if server.startswith("https"):
        if cluster.get("insecure-skip-tls-verify"):
            ssl_ctx = ssl._create_unverified_context()
        else:
            ssl_ctx = ssl.create_default_context()
            ca_data = cluster.get("certificate-authority-data")
            if ca_data:
                ssl_ctx.load_verify_locations(
                    cadata=base64.b64decode(ca_data).decode())
            elif cluster.get("certificate-authority"):
                ssl_ctx.load_verify_locations(cluster["certificate-authority"])
        cert_data = user.get("client-certificate-data")
        key_data = user.get("client-key-data")
        if cert_data and key_data:
            # ssl wants file paths; decode the inline pair to a temp bundle,
            # and unlink it as soon as the context has loaded it — private
            # key material must not outlive this call on disk (ADVICE r3)
            bundle = tempfile.NamedTemporaryFile(
                mode="w", suffix=".pem", delete=False)
            try:
                bundle.write(base64.b64decode(cert_data).decode())
                bundle.write("\n")
                bundle.write(base64.b64decode(key_data).decode())
                bundle.close()
                ssl_ctx.load_cert_chain(bundle.name)
            finally:
                os.unlink(bundle.name)
        elif user.get("client-certificate") and user.get("client-key"):
            ssl_ctx.load_cert_chain(user["client-certificate"],
                                    user["client-key"])
    return server, user.get("token"), ssl_ctx


class HttpKubeStore:
    """CoordinationPlane over HTTP. Call start() to seed + watch."""

    KINDS = KubeStore.KINDS
    namespace = "default"

    # A pooled socket idle longer than this is dropped before reuse rather
    # than risk racing the server's own keep-alive reaper: the server may
    # close an idle connection at any moment, and a write that lands in
    # that window dies response-phase — the ambiguous "did it apply?"
    # failure mode. Under the default apiserver/LB idle timeouts (60-300s)
    # a 30s client horizon means we always blink first.
    KEEPALIVE_IDLE_SECONDS = 30.0

    # watch-ingest attribution: decode/apply wall time flushes as one
    # synthesized span pair per this many events (per-event spans would
    # flood the trace ring during a 10k-pod ingest)
    INGEST_SPAN_BATCH = 256

    def __init__(self, server: str, token: Optional[str] = None,
                 verify_tls: bool = True, timeout: float = 10.0,
                 ssl_context=None, registry=None, clock=None,
                 keepalive_idle_seconds: Optional[float] = None):
        self.server = server.rstrip("/")
        self.token = token
        self.timeout = timeout
        self._clock = clock  # injectable (FakeClock in tests); None = time.monotonic
        self.keepalive_idle_seconds = (
            self.KEEPALIVE_IDLE_SECONDS if keepalive_idle_seconds is None
            else keepalive_idle_seconds)
        self._ssl = ssl_context
        if self._ssl is None and server.startswith("https") and not verify_tls:
            self._ssl = ssl._create_unverified_context()
        self._cache = KubeStore()  # informer cache + watcher fan-out
        # wire-client observability (designs/metrics.md): request outcomes
        # at the HTTP boundary and watch reconnects — the dashboards' first
        # question when a controller goes quiet is "is the watch alive".
        # Injectable registry like every controller (tests isolate counts).
        reg = registry if registry is not None else REGISTRY
        self.requests_total = reg.counter(
            f"{NAMESPACE}_coordination_requests_total",
            "Coordination-plane HTTP requests.", ("method", "outcome"))
        self.watch_restarts = reg.counter(
            f"{NAMESPACE}_coordination_watch_restarts_total",
            "Watch streams re-established (any cause incl. clean "
            "server-side timeouts).", ("kind",))
        self._admission = None
        # fencing high-water mark as advertised by the server on every
        # response (X-Fencing-Epoch); 0 until the first round trip
        self._fence_epoch = 0
        self._docs: "dict[tuple[str, str], dict]" = {}  # last manifest seen
        self._rv: "dict[tuple[str, str], int]" = {}     # last rv applied
        self._lock = threading.RLock()
        self._stop = threading.Event()
        self._threads: "list[threading.Thread]" = []
        # keep-alive connection pool for full-body requests (_request_json):
        # one reusable connection per thread. A fresh TCP (+TLS) handshake
        # per write capped the wire drain at ~10 ops/s in the deployed-
        # topology benchmark (benchmarks/wire_bench.py); keep-alive is what
        # a real client library does. Watch streams stay on urllib — they
        # hold a connection open indefinitely and never return it usable.
        split = urllib.parse.urlsplit(self.server)
        self._netloc = split.netloc
        self._https = split.scheme == "https"
        self._pool_local = threading.local()
        # resilience.RetryPolicy for the kube-apiserver edge (operator wires
        # it): the transparent reconnect retry below spends from its budget
        # and every unreachable outcome feeds its breaker
        self._policy = None

    def set_resilience(self, policy) -> None:
        self._policy = policy

    @classmethod
    def from_kubeconfig(cls, path: str, **kw) -> "HttpKubeStore":
        server, token, ssl_ctx = load_kubeconfig(path)
        return cls(server, token=token, ssl_context=ssl_ctx, **kw)

    # -- HTTP plumbing ---------------------------------------------------------

    def _url(self, kind: str, name: Optional[str] = None,
             sub: Optional[str] = None, query: str = "") -> str:
        prefix, plural, namespaced = _API[kind]
        path = prefix
        if namespaced:
            path += f"/namespaces/{self.namespace}"
        path += f"/{plural}"
        if name:
            path += f"/{name}"
        if sub:
            path += f"/{sub}"
        if query:
            path += f"?{query}"
        return self.server + path

    def _request(self, method: str, url: str, body: "Optional[dict]" = None,
                 timeout: "Optional[float]" = None,
                 content_type: str = "application/json"):
        data = None if body is None else json.dumps(body).encode()
        req = urllib.request.Request(url, data=data, method=method)
        req.add_header("Content-Type", content_type)
        if self.token:
            req.add_header("Authorization", f"Bearer {self.token}")
        try:
            resp = urllib.request.urlopen(
                req, timeout=timeout or self.timeout, context=self._ssl)
        except urllib.error.HTTPError as e:
            msg = e.read().decode(errors="replace")[:300]
            if e.code == 409:
                self.requests_total.inc(method=method, outcome="conflict")
                raise Conflict(msg)
            if e.code == 429:
                # throttled is its own outcome (not lumped with 5xx): the
                # server is ALIVE and pacing us — honor its Retry-After
                # through the policy's clamped, FakeClock-injectable sleep
                self.requests_total.inc(method=method, outcome="throttled")
                ra = _retry_after_seconds(e.headers.get("Retry-After"))
                if ra is not None and self._policy is not None:
                    self._policy.sleep_retry_after(ra)
                raise ApiError(e.code, msg, retry_after=ra)
            self.requests_total.inc(method=method, outcome=f"http_{e.code}")
            raise ApiError(e.code, msg)
        except urllib.error.URLError as e:
            self.requests_total.inc(method=method, outcome="unreachable")
            raise ApiError(0, f"apiserver unreachable: {e.reason}")
        self.requests_total.inc(method=method, outcome="ok")
        return resp

    def _conn_now(self) -> float:
        return self._clock.now() if self._clock is not None \
            else time.monotonic()

    def _pooled_conn(self) -> "tuple[http.client.HTTPConnection, bool]":
        """(connection, fresh): fresh=True means it was just connected —
        nothing has ever been sent on it. Raises OSError family on
        connect failure (caller maps to ApiError(0)). A connection idle
        past keepalive_idle_seconds is proactively dropped and redialed
        (see KEEPALIVE_IDLE_SECONDS)."""
        c = getattr(self._pool_local, "conn", None)
        if c is not None:
            idle = self._conn_now() - getattr(
                self._pool_local, "last_used", self._conn_now())
            if self.keepalive_idle_seconds >= 0 \
                    and idle > self.keepalive_idle_seconds:
                self._drop_pooled_conn()
                c = None
        if c is not None:
            self._pool_local.last_used = self._conn_now()
            return c, False
        if self._https:
            c = http.client.HTTPSConnection(
                self._netloc, timeout=self.timeout, context=self._ssl)
        else:
            c = http.client.HTTPConnection(
                self._netloc, timeout=self.timeout)
        c.connect()
        # TCP_NODELAY: http.client writes headers and body as separate
        # small segments; with Nagle on, the second segment waits out
        # the peer's delayed ACK (~40ms) — at controller write rates
        # that stall IS the wire benchmark's whole budget
        c.sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        self._pool_local.conn = c
        self._pool_local.last_used = self._conn_now()
        return c, True

    def _drop_pooled_conn(self) -> None:
        c = getattr(self._pool_local, "conn", None)
        if c is not None:
            self._pool_local.conn = None
            try:
                c.close()
            except OSError:
                pass

    def _request_json(self, method, url, body=None,
                      content_type: str = "application/json",
                      epoch: "Optional[int]" = None):
        """Full-body request over the per-thread keep-alive connection.
        The response is always consumed completely, so the socket stays
        reusable; a stale pooled socket (server closed it between calls)
        gets ONE transparent reconnect. `epoch` rides as X-Fencing-Epoch:
        the server refuses the write (409 Fenced) when it is older than
        the fencing high-water mark."""
        data = None if body is None else json.dumps(body).encode()
        headers = {"Content-Type": content_type}
        if epoch is not None:
            headers["X-Fencing-Epoch"] = str(epoch)
        if self.token:
            headers["Authorization"] = f"Bearer {self.token}"
        split = urllib.parse.urlsplit(url)
        path = split.path + (f"?{split.query}" if split.query else "")
        pol = self._policy
        if pol is not None and pol.breaker is not None \
                and not pol.breaker.allow():
            # apiserver known-down: fail fast instead of burning a connect
            # timeout per call (the breaker's half-open probe lets ONE call
            # through per recovery window)
            pol.retries_total.inc(dep=pol.dep, outcome="breaker_open")
            self.requests_total.inc(method=method, outcome="breaker_open")
            raise ApiError(0, "apiserver circuit breaker open")

        def _note_failure():
            if pol is not None:
                pol.note_failure()

        def _retry_ok():
            # the transparent reconnect retry also spends a budget token —
            # a flapping apiserver can't be retried into a storm
            return pol is None or pol.try_retry()

        for attempt in (0, 1):
            try:
                conn, fresh = self._pooled_conn()
            except (http.client.HTTPException, ConnectionError, OSError) as e:
                # connect-phase failure: nothing was sent, retrying any
                # method is safe; exhausted -> the documented contract
                _note_failure()
                if attempt == 0 and _retry_ok():
                    continue
                self.requests_total.inc(method=method, outcome="unreachable")
                raise ApiError(0, f"apiserver unreachable: {e}")
            try:
                conn.request(method, path, body=data, headers=headers)
            except (http.client.HTTPException, ConnectionError, OSError) as e:
                # SEND-phase failure: the request never left intact. GETs
                # always retry; writes retry only for the stale-keep-alive
                # case (a REUSED socket the server closed between calls —
                # the send died cleanly, nothing was applied). A timeout
                # here still means nothing was delivered, but stay
                # conservative and exclude it for writes.
                self._drop_pooled_conn()
                _note_failure()
                retriable = (method == "GET"
                             or (not fresh and not isinstance(e, TimeoutError)))
                if attempt == 0 and retriable and _retry_ok():
                    continue
                self.requests_total.inc(method=method, outcome="unreachable")
                raise ApiError(0, f"apiserver unreachable: {e}")
            try:
                resp = conn.getresponse()
                payload = resp.read()
            except (http.client.HTTPException, ConnectionError, OSError) as e:
                # RESPONSE-phase failure: the request WAS delivered and may
                # have been applied — re-sending a write would double-apply
                # (a CAS would see its own rv bump as a spurious Conflict,
                # a create would 409 AlreadyExists against itself). Only
                # idempotent GETs retry past this point — with ONE carve-out:
                # RemoteDisconnected on a REUSED socket. getresponse raises it
                # only when ZERO response bytes arrived, and a server that
                # processed a request sends at least a status line before
                # closing; an immediate FIN on a pooled connection is the
                # stale-keep-alive race (server expired the idle socket as our
                # request was in flight — it never read it), so one replay of
                # a write is safe.
                self._drop_pooled_conn()
                _note_failure()
                retriable = (method == "GET"
                             or (not fresh
                                 and isinstance(e, http.client.RemoteDisconnected)))
                if attempt == 0 and retriable and _retry_ok():
                    continue
                self.requests_total.inc(method=method, outcome="unreachable")
                raise ApiError(0, f"apiserver unreachable: {e}")
            if resp.will_close:
                self._drop_pooled_conn()
            # ANY response means the apiserver is alive: 4xx/409 are
            # business outcomes, not dependency failures — the breaker and
            # budget only ever see transport-level unreachability
            if pol is not None:
                pol.note_success()
            fe = resp.getheader("X-Fencing-Epoch")
            if fe is not None:
                try:
                    self._fence_epoch = max(self._fence_epoch, int(fe))
                except ValueError:
                    pass
            if resp.status == 409:
                self.requests_total.inc(method=method, outcome="conflict")
                text = payload.decode(errors="replace")[:300]
                if '"Fenced"' in text:
                    raise Fenced(text)
                raise Conflict(text)
            if resp.status == 429:
                # see _request: throttled is a pacing signal from a LIVE
                # server, classified apart from 5xx and honored via the
                # policy's clamped Retry-After sleep
                self.requests_total.inc(method=method, outcome="throttled")
                ra = _retry_after_seconds(resp.getheader("Retry-After"))
                if ra is not None and pol is not None:
                    pol.sleep_retry_after(ra)
                raise ApiError(resp.status,
                               payload.decode(errors="replace")[:300],
                               retry_after=ra)
            if resp.status >= 400:
                self.requests_total.inc(method=method,
                                        outcome=f"http_{resp.status}")
                raise ApiError(resp.status,
                               payload.decode(errors="replace")[:300])
            self.requests_total.inc(method=method, outcome="ok")
            return json.loads(payload or b"{}")

    # -- informer lifecycle ----------------------------------------------------

    # kinds the informer LISTs + watches. "events" is deliberately excluded:
    # a busy cluster's event firehose (kubelet, scheduler, every component)
    # would flood the cache and fire every watcher with objects no
    # controller reads — our own writes still land in the cache via the
    # read-your-writes apply, and listings of foreign events go direct.
    WATCHED_KINDS = tuple(k for k in KubeStore.KINDS if k != "events")

    def start(self) -> None:
        """Seed the cache with LIST, then keep it current with one watch
        stream per kind (reconnect-with-relist on drop)."""
        for kind in self.WATCHED_KINDS:
            self._relist(kind)
        for kind in self.WATCHED_KINDS:
            t = threading.Thread(target=self._watch_loop, args=(kind,),
                                 name=f"watch-{kind}", daemon=True)
            t.start()
            self._threads.append(t)

    def stop(self) -> None:
        self._stop.set()
        # watch threads are daemons blocked on reads; they die with the
        # process or on the next bookmark tick

    def _relist(self, kind: str) -> None:
        doc = self._request_json("GET", self._url(kind))
        fresh = {}
        for item in doc.get("items", []):
            name = serde.manifest_name(item)
            if name:
                fresh[name] = item
        with self._lock:
            stale = {n for n in (o for o in self._cache._objects[kind])
                     if n not in fresh}
            for name, item in fresh.items():
                self._apply_manifest(kind, "MODIFIED", item, notify=True)
            for name in stale:
                obj = self._cache.delete(kind, name)
                self._rv.pop((kind, name), None)
                self._docs.pop((kind, name), None)

    def _watch_loop(self, kind: str) -> None:
        attached_before = False
        while not self._stop.is_set():
            if attached_before:
                # ANY re-entry is a restart — kube-apiserver ends long
                # watches with a clean close, which must count too
                self.watch_restarts.inc(kind=kind)
            attached_before = True
            # Watch-ingest attribution (docs/designs/slo.md): per-event
            # spans would flood the trace ring at 10k-pod ingest rates, so
            # decode (json.loads) and apply (cache + watcher fan-out) wall
            # time accumulate and flush as ONE synthesized span per batch —
            # the deployed topology's dominant cycle cost becomes a
            # first-class phase instead of dark time.
            from ..tracing import TRACER

            decode_s = apply_s = 0.0
            batched = 0

            def flush_ingest():
                nonlocal decode_s, apply_s, batched
                if not batched:
                    return
                TRACER.record_span("ingest.decode", decode_s,
                                   kind=kind, events=batched)
                TRACER.record_span("ingest.apply", apply_s,
                                   kind=kind, events=batched)
                decode_s = apply_s = 0.0
                batched = 0

            try:
                resp = self._request("GET", self._url(kind, query="watch=true"),
                                     timeout=86400)
                with resp:
                    # close the start()-to-attach gap: an object deleted
                    # before this stream attached produced no DELETED event
                    # and is absent from the attach replay — only a relist
                    # AFTER the stream opened evicts it from the cache
                    self._relist(kind)
                    for line in resp:
                        if self._stop.is_set():
                            flush_ingest()
                            return
                        if not line.strip():
                            continue
                        t0 = time.perf_counter()
                        event = json.loads(line)
                        t1 = time.perf_counter()
                        decode_s += t1 - t0
                        if event.get("type") == "BOOKMARK":
                            continue
                        self._apply_manifest(
                            kind, event["type"], event.get("object") or {},
                            notify=True)
                        apply_s += time.perf_counter() - t1
                        batched += 1
                        if batched >= self.INGEST_SPAN_BATCH:
                            flush_ingest()
                flush_ingest()  # clean server-side close: drain the batch
            except (ApiError, Conflict, OSError, ValueError) as e:
                flush_ingest()  # the partial batch's time is still real
                if self._stop.is_set():
                    return
                log.warning("watch %s dropped (%s); relisting", kind, e)
                self._stop.wait(0.5)
                try:
                    self._relist(kind)
                except Exception as e2:
                    log.warning("relist %s failed: %s", kind, e2)
                    self._stop.wait(1.0)

    def _apply_manifest(self, kind: str, type_: str, doc: dict,
                        notify: bool) -> None:
        name = serde.manifest_name(doc)
        if not name:
            return
        key = (kind, name)
        with self._lock:
            if type_ == "DELETED":
                self._rv.pop(key, None)
                self._docs.pop(key, None)
                self._cache.delete(kind, name)  # notifies cache watchers
                return
            rv = int((doc.get("metadata") or {}).get("resourceVersion") or 0)
            if rv and self._rv.get(key, -1) >= rv:
                return  # stale echo of a write already applied
            self._rv[key] = rv
            self._docs[key] = doc
            obj = serde.from_manifest(kind, doc)
            if obj is None:
                # foreign object of a controller-owned kind: visible on the
                # server, not interpretable here — leave it out of the cache
                log.debug("skipping foreign %s/%s (no embedded model)",
                          kind, name)
                return
            if self._cache.get(kind, name) is None:
                self._cache.create(kind, name, obj)
            else:
                self._cache.update(kind, name, obj)

    # -- CoordinationPlane: CRUD ----------------------------------------------

    def _admit(self, kind: str, obj, op: str):
        if self._admission is not None:
            return self._admission(kind, obj, op)
        return obj

    def fence_epoch(self) -> int:
        """Highest fencing epoch the server has advertised to this client.
        Lags the authoritative server-side mark by at most one request —
        callers minting epochs (LeaderElector) also consult the lease
        object itself, which the same watch keeps current."""
        return self._fence_epoch

    def get(self, kind: str, name: str):
        return self._cache.get(kind, name)

    def list(self, kind: str) -> list:
        if kind not in self.WATCHED_KINDS:
            # unwatched kinds (events) never enter the informer cache, so a
            # cache read would always be empty — and Operator's event prune
            # would never see orphaned evt-* objects from crashed replicas.
            # Serve these with a direct LIST instead (ADVICE r3).
            doc = self._request_json("GET", self._url(kind))
            out = []
            for item in doc.get("items", []):
                obj = serde.from_manifest(kind, item)
                if obj is not None:
                    out.append(obj)
            return out
        return self._cache.list(kind)

    def create(self, kind: str, name: str, obj,
               epoch: "Optional[int]" = None) -> None:
        obj = self._admit(kind, obj, "CREATE")
        doc = serde.to_manifest(kind, name, obj)
        created = self._request_json("POST", self._url(kind), doc,
                                     epoch=epoch)
        self._apply_manifest(kind, "ADDED", created, notify=True)

    def update(self, kind: str, name: str, obj,
               epoch: "Optional[int]" = None) -> None:
        obj = self._admit(kind, obj, "UPDATE")
        doc = serde.to_manifest(kind, name, obj)
        updated = self._request_json("PUT", self._url(kind, name), doc,
                                     epoch=epoch)
        self._apply_manifest(kind, "MODIFIED", updated, notify=True)

    def delete(self, kind: str, name: str, epoch: "Optional[int]" = None):
        obj = self._cache.get(kind, name)
        try:
            self._request_json("DELETE", self._url(kind, name), epoch=epoch)
        except ApiError as e:
            if e.code != 404:
                raise
        self._apply_manifest(kind, "DELETED",
                             {"metadata": {"name": name}}, notify=True)
        return obj

    def compare_and_swap(self, kind: str, name: str, expect, obj,
                         epoch: "Optional[int]" = None) -> None:
        obj = self._admit(kind, obj, "UPDATE")
        with self._lock:
            cur = self._cache.get(kind, name)
            if cur is not expect:
                raise Conflict(f"{kind}/{name} changed since read")
            doc_rv = (self._docs.get((kind, name), {}).get("metadata") or {}
                      ).get("resourceVersion")
        doc = serde.to_manifest(kind, name, obj)
        if doc_rv is not None:
            doc["metadata"]["resourceVersion"] = doc_rv  # server-side CAS
        updated = self._request_json("PUT", self._url(kind, name), doc,
                                     epoch=epoch)
        self._apply_manifest(kind, "MODIFIED", updated, notify=True)

    def delete_if(self, kind: str, name: str, expect,
                  epoch: "Optional[int]" = None) -> bool:
        """Atomic over the wire: the DELETE carries a resourceVersion
        precondition, so a successor's write between our check and the
        delete loses nothing (a lease released late must never clobber the
        new holder's lease)."""
        with self._lock:
            if self._cache.get(kind, name) is not expect:
                return False
            rv = (self._docs.get((kind, name), {}).get("metadata") or {}
                  ).get("resourceVersion")
        try:
            self._request_json(
                "DELETE", self._url(kind, name),
                None if rv is None else
                {"preconditions": {"resourceVersion": rv}}, epoch=epoch)
        except Conflict:
            return False
        except ApiError as e:
            if e.code != 404:
                raise
        self._apply_manifest(kind, "DELETED",
                             {"metadata": {"name": name}}, notify=True)
        return True

    # -- watch + admission -----------------------------------------------------

    def watch(self, fn: Callable[[str, str, object], None]) -> None:
        self._cache.watch(fn)

    def unwatch(self, fn) -> None:
        self._cache.unwatch(fn)

    def set_admission(self, fn) -> None:
        self._admission = fn

    # -- typed reads (served from the informer cache) --------------------------

    def pods(self):
        return self._cache.pods()

    def pending_pods(self):
        return self._cache.pending_pods()

    def daemon_pods(self):
        return self._cache.daemon_pods()

    def nodes(self):
        return self._cache.nodes()

    def machines(self):
        return self._cache.machines()

    def provisioners(self):
        return self._cache.provisioners()

    def nodetemplates(self):
        return self._cache.nodetemplates()

    def pdbs(self):
        return self._cache.pdbs()

    # -- subresources ----------------------------------------------------------

    def cordon_node(self, name: str) -> None:
        """Server-side cordon: a merge-PATCH (RFC 7386) of ONLY
        spec.unschedulable, so the kubelet-owned Node object is never
        replaced wholesale — the real kube-scheduler must stop targeting
        a draining node. NB merge-patch replaces list fields wholesale;
        never extend this to taints without strategic-merge."""
        self._patch_unschedulable(name, True)

    def uncordon_node(self, name: str) -> None:
        """Roll back a cordon (consolidation revalidation failure): the
        node stays in service, so spec.unschedulable must clear or the
        real scheduler would shun healthy capacity forever."""
        self._patch_unschedulable(name, None)  # merge-patch null deletes

    def _patch_unschedulable(self, name: str, value) -> None:
        doc = self._request_json(
            "PATCH", self._url("nodes", name),
            {"spec": {"unschedulable": value}},
            content_type="application/merge-patch+json")
        # same read-your-writes path as every other write: record rv + doc
        # and refresh the cache object (fires cache watchers); the watch
        # echo then dedupes by resourceVersion
        self._apply_manifest("nodes", "MODIFIED", doc, notify=True)

    def bind_pod(self, pod_name: str, node_name: str,
                 epoch: "Optional[int]" = None) -> None:
        self._request_json(
            "POST", self._url("pods", pod_name, sub="binding"),
            {"apiVersion": "v1", "kind": "Binding",
             "metadata": {"name": pod_name},
             "target": {"apiVersion": "v1", "kind": "Node",
                        "name": node_name}}, epoch=epoch)
        # read-your-writes without waiting for the watch echo
        with self._lock:
            pod = self._cache.get("pods", pod_name)
            if pod is not None and not pod.node_name:
                import dataclasses

                self._cache.update("pods", pod_name,
                                   dataclasses.replace(pod, node_name=node_name))
