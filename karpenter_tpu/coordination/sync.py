"""Fixture sync: make a coordination plane match a manifest set.

The reference keeps its test clusters in sync with the repo's fixture
directory via a GitOps loop (test/cmd/sync-cluster bootstraps it;
test/infrastructure/clusters/test-infra is the synced path). The hermetic
analogue is one idempotent pass: apply every object from the manifests
(create or update), and with prune=True delete managed-kind objects the
fixture no longer names.

Works against any store with the shared create/update/delete/list API —
the in-process KubeStore, the mini apiserver, or a real cluster through
HttpKubeStore.
"""

from __future__ import annotations

import logging

log = logging.getLogger("karpenter.sync")


def _is_conflict(e: Exception) -> bool:
    """Already-exists, from either store flavor (a lost create race)."""
    from ..fake.kube import Conflict

    if isinstance(e, Conflict):
        return True
    return getattr(e, "code", None) == 409  # httpkube ApiError

# kinds a fixture set manages, in apply order (templates before the
# provisioners that reference them; pods last so admission sees their
# provisioner); prune runs in reverse
_KIND_ORDER = ("nodetemplates", "provisioners", "pdbs", "pods")


def sync_manifests(kube, loaded, prune: bool = False) -> "dict[str, int]":
    """One sync pass; returns {created, updated, pruned, unchanged} counts.

    `loaded` is an apis.yaml_compat.LoadedManifests. Conflicted creates
    fall back to update (last-writer-wins, like a kubectl apply); prune
    only touches the managed kinds so foreign objects (machines, nodes,
    leases, events) are never swept.
    """
    desired: "dict[str, dict[str, object]]" = {
        "nodetemplates": {t.name: t for t in loaded.templates},
        "provisioners": {p.name: p for p in loaded.provisioners},
        "pdbs": {p.name: p for p in loaded.pdbs},
        "pods": {p.name: p for p in loaded.pods},
    }
    counts = {"created": 0, "updated": 0, "pruned": 0, "unchanged": 0}
    for kind in _KIND_ORDER:
        for name, obj in desired[kind].items():
            current = kube.get(kind, name)
            if current is None:
                try:
                    kube.create(kind, name, obj)
                    counts["created"] += 1
                    continue
                except Exception as e:
                    if not _is_conflict(e):
                        raise  # admission denial / server error: surface it
                    current = kube.get(kind, name)  # lost a create race
            if kind == "pods":
                # an existing pod may be BOUND: stomping it with the
                # fixture's pending copy would silently unbind workload
                counts["unchanged"] += 1
                continue
            if current == obj:
                counts["unchanged"] += 1
                continue
            kube.update(kind, name, obj)
            counts["updated"] += 1
    if prune:
        for kind in reversed(_KIND_ORDER):
            if kind == "pods":
                # never prune pods: bound workload pods are cluster state,
                # not fixture state (the fixture only seeds pending ones)
                continue
            for obj in list(kube.list(kind)):
                name = getattr(obj, "name", None)
                if name is not None and name not in desired[kind]:
                    kube.delete(kind, name)
                    counts["pruned"] += 1
    log.info("sync: %s", counts)
    return counts
