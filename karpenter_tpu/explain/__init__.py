"""Decision-provenance (explain) plane (ISSUE 14).

Answers *why* for every solve the observability arc already times: why a
pod landed on a node type (assignment + winning bucket rung), why a pod
is unschedulable (per-dimension mask attribution, parity-audited against
the scalar oracle), why consolidation kept or evicted a node (verdict +
cost delta), and why the fleet shed a solve — one schema-versioned
DecisionRecord per decision in a bounded ring, each carrying its solve's
trace id.

Surfaces: ``GET /debug/decisions`` (index + ``?id=`` detail +
``?pod=`` lookup), ``python -m karpenter_tpu explain <pod>``, statusz
schema-8 ``decisions`` section, flight-recorder bundles, and
``karpenter_decisions_*`` metrics. The plane is advisory and strict-noop
when disabled (``KARPENTER_TPU_EXPLAIN=0``) — chaos-invariant-enforced
(``explain-strict-noop``); the attribution pass is lazy/on-demand only,
never on the solve hot path (``make explain-drill`` records the ON/OFF
solve p50 delta).
"""
from __future__ import annotations

from .records import (DECISIONS, SCHEMA_VERSION, note_drain,  # noqa: F401
                      note_shed)
from .reasons import (CLAUSES, CONSOLIDATION_VERDICTS,  # noqa: F401
                      DIMENSIONS, DRAIN_REASONS, SHED_REASONS, clause_for)
from .state import disabled, enabled, set_enabled  # noqa: F401


def attribute_pod(*args, **kwargs) -> dict:
    """Lazy wrapper over attribution.attribute_pod (keeps this package
    import-light for statusz/serving; the pass itself pulls in numpy and
    the encode substrate)."""
    from .attribution import attribute_pod as impl

    return impl(*args, **kwargs)


def activity() -> dict:
    """Monotonic activity counters + ring length — the chaos
    ``explain-strict-noop`` invariant diffs two of these."""
    return DECISIONS.activity()


def snapshot() -> dict:
    """The statusz schema-8 ``decisions`` section (also bundled by the
    flight recorder)."""
    act = DECISIONS.activity()
    recent = DECISIONS.records(limit=5)
    return {
        "enabled": enabled(),
        "schema": SCHEMA_VERSION,
        "records_total": act["records_total"],
        "attributions_total": act["attributions_total"],
        "sheds_total": act["sheds_total"],
        "consolidations_total": act["consolidations_total"],
        "drains_total": act["drains_total"],
        "ring_depth": act["ring"],
        "dimensions": list(DIMENSIONS),
        "recent": [{"id": r.get("id"), "kind": r.get("kind"),
                    "ts": r.get("ts"), "trace_id": r.get("trace_id")}
                   for r in recent],
    }
