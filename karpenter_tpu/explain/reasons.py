"""The decision-reason vocabulary: one name per admission-mask dimension.

The dense formulation makes attribution nearly free — every rejection is
already a zero in a named mask factor — but only if the NAMES stay
honest. This module is the single registry: the constraint dimensions (in
the encoder's first-rejection order), the verbatim scalar-oracle clause
each dimension maps onto (models/encode.py diagnose_unschedulable — the
mapping is string-exact so the parity audit can compare verdicts with
``==``), the fleet shed reasons, and the consolidation keep/evict
verdicts.

Every table here is a module-level PURE LITERAL:
hack/check_decision_reasons.py AST-parses this file (no package import,
no jax) and fails presubmit when the vocabulary drifts from
solver/core.py MASK_DIMENSIONS, from the oracle's clause strings, or
from the call sites that cite verdicts/shed reasons.
"""
from __future__ import annotations

# Constraint dimensions in the admission rule's first-rejection order
# (the order diagnose_unschedulable walks its stages). Must equal
# solver/core.py MASK_DIMENSIONS — lint-enforced.
DIMENSIONS = (
    "taints",
    "requirements",
    "resources",
    "availability",
    "diversity",
    "constraints",
)

# dimension -> the scalar oracle's verbatim clause. These strings are the
# EXACT literals diagnose_unschedulable returns; the attribution pass and
# the oracle are parity-audited on string equality, so editing one side
# without the other fails both the lint and tests/test_explain.py.
CLAUSES = (
    ("taints",
     "pod does not tolerate the taints of any provisioner"),
    ("requirements",
     "pod requirements are incompatible with every "
     "provisioner and instance type"),
    ("resources",
     "resource requests do not fit any compatible instance type"),
    ("availability",
     "every compatible offering is currently unavailable "
     "(insufficient capacity)"),
    ("diversity",
     "every remaining compatible offering is barred by the spot "
     "diversity floor this cycle"),
    ("constraints",
     "compatible capacity exists but scheduling constraints "
     "(affinity/topology/limits) were unsatisfiable this cycle"),
)

# Fleet shed causes (fleet/frontend.py and fleet/failover.py note_shed
# call sites cite these literally; the storm drill asserts every
# admission/queue shed in the artifact carries one, the partition drill
# asserts the quarantine shed does, and the churn drill asserts the
# overload plane's sheds cite the overload-* rows).
SHED_REASONS = (
    "deadline",
    "poison-quarantine",
    "overload-pressure",
    "overload-queue-overflow",
    "overload-brownout",
)

# Node drain causes (controllers/interruption cites the reactive one per
# handled reclaim message, spot/rebalance.py cites the proactive one per
# ahead-of-reclaim replace; the spot-storm drill audits attribution from
# the drain-throughput histogram's matching `reason` label).
DRAIN_REASONS = (
    "reactive-reclaim",
    "proactive-rebalance",
)

# Consolidation keep/evict verdicts (ops/consolidate.py cites these per
# candidate lane; "delete"/"replace" are the evict outcomes, the rest are
# keep branches in ladder order).
CONSOLIDATION_VERDICTS = (
    "unschedulable-pods",
    "opens-more-than-one-node",
    "spot-replace-barred",
    "no-cheaper-option",
    "delete",
    "replace",
)

CLAUSE_OF = dict(CLAUSES)
DIMENSION_OF_CLAUSE = {clause: dim for dim, clause in CLAUSES}


def clause_for(dimension: str) -> str:
    """The oracle clause a dominant dimension maps onto."""
    return CLAUSE_OF[dimension]
