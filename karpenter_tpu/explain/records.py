"""Schema-versioned DecisionRecords in a bounded ring.

One DecisionRecord per solve (kind ``provisioning``), per consolidation
pass (kind ``consolidation``), and per fleet shed (kind ``shed``), each
carrying the solve's trace id so ``/debug/traces?id=`` resolves the
record back to its spans. The ring is bounded
(``KARPENTER_TPU_EXPLAIN_RING``, default 256) and thread-safe; the
flight recorder embeds its tail in every diagnostics bundle and
``GET /debug/decisions`` serves it live.

Every write path guards :func:`state.enabled` — with the plane disabled
nothing here moves (counters, ring, metrics), which is exactly what the
chaos ``explain-strict-noop`` invariant diffs.
"""
from __future__ import annotations

import os
import threading
import time
from collections import deque
from typing import Optional

from ..metrics import REGISTRY
from . import state

SCHEMA_VERSION = 1

DEFAULT_RING = 256
RING_ENV = "KARPENTER_TPU_EXPLAIN_RING"


def _ring_size() -> int:
    try:
        return max(1, int(os.environ.get(RING_ENV, DEFAULT_RING)))
    except ValueError:
        return DEFAULT_RING


RECORDS_TOTAL = REGISTRY.counter(
    "karpenter_decisions_records_total",
    "DecisionRecords emitted into the explain ring", ("kind",))
UNSCHEDULABLE_REASONS = REGISTRY.counter(
    "karpenter_decisions_unschedulable_total",
    "Unassigned-pod attributions by dominant constraint dimension",
    ("dimension",))
RING_DEPTH = REGISTRY.gauge(
    "karpenter_decisions_ring_depth",
    "DecisionRecords currently resident in the explain ring")
ATTRIBUTION_SECONDS = REGISTRY.histogram(
    "karpenter_decisions_attribution_seconds",
    "Wall time of one per-pod mask-attribution pass (lazy, off the "
    "solve hot path)")


class DecisionRing:
    """Bounded, thread-safe ring of DecisionRecords with monotonic ids."""

    def __init__(self, maxlen: "Optional[int]" = None):
        self._lock = threading.Lock()
        self._ring: "deque[dict]" = deque(maxlen=maxlen or _ring_size())
        self._next_id = 0
        # monotonic activity counters — the chaos strict-noop invariant
        # diffs these across a disabled-plane scenario
        self.records_total = 0
        self.attributions_total = 0
        self.sheds_total = 0
        self.consolidations_total = 0
        self.drains_total = 0

    def emit(self, kind: str, record: dict,
             ts: "Optional[float]" = None) -> "Optional[str]":
        """Stamp + append one record; returns its id, or None when the
        plane is disabled (strict-noop: nothing moves)."""
        if not state.enabled():
            return None
        with self._lock:
            rid = f"d-{self._next_id}"
            self._next_id += 1
            rec = {"schema": SCHEMA_VERSION, "id": rid, "kind": kind,
                   "ts": time.time() if ts is None else ts, **record}
            self._ring.append(rec)
            self.records_total += 1
            if kind == "shed":
                self.sheds_total += 1
            elif kind == "consolidation":
                self.consolidations_total += 1
            elif kind == "drain":
                self.drains_total += 1
            depth = len(self._ring)
        RECORDS_TOTAL.inc(kind=kind)
        RING_DEPTH.set(depth)
        return rid

    def note_attribution(self, seconds: float, dimension: str) -> None:
        """Account one completed per-pod attribution pass."""
        if not state.enabled():
            return
        with self._lock:
            self.attributions_total += 1
        ATTRIBUTION_SECONDS.observe(max(0.0, seconds))
        UNSCHEDULABLE_REASONS.inc(dimension=dimension)

    def get(self, rid: str) -> "Optional[dict]":
        with self._lock:
            for rec in self._ring:
                if rec.get("id") == rid:
                    return rec
        return None

    def records(self, limit: "Optional[int]" = None,
                kind: "Optional[str]" = None) -> "list[dict]":
        """Newest-last tail of the ring, optionally filtered by kind."""
        with self._lock:
            out = [r for r in self._ring
                   if kind is None or r.get("kind") == kind]
        return out if limit is None else out[-max(0, limit):]

    def find_pod(self, pod: str) -> "Optional[dict]":
        """Newest record mentioning pod `pod` (by assignment or
        unassigned attribution) — the `explain <pod>` CLI's lookup."""
        with self._lock:
            ring = list(self._ring)
        for rec in reversed(ring):
            for u in rec.get("unassigned", ()):
                if u.get("pod") == pod:
                    return rec
            for a in rec.get("assignments", ()):
                if pod in a.get("pods", ()):
                    return rec
        return None

    def ring_len(self) -> int:
        with self._lock:
            return len(self._ring)

    def activity(self) -> dict:
        with self._lock:
            return {
                "records_total": self.records_total,
                "attributions_total": self.attributions_total,
                "sheds_total": self.sheds_total,
                "consolidations_total": self.consolidations_total,
                "drains_total": self.drains_total,
                "ring": len(self._ring),
            }

    def clear(self) -> None:
        """Drop resident records (tests); monotonic counters stay."""
        with self._lock:
            self._ring.clear()


DECISIONS = DecisionRing()


def note_shed(tenant: str, where: str, reason: str,
              ts: "Optional[float]" = None) -> "Optional[str]":
    """One fleet shed cause into the ring (fleet/frontend.py cites a
    reasons.SHED_REASONS literal — lint-enforced)."""
    if not state.enabled():
        return None
    return DECISIONS.emit(
        "shed", {"tenant": tenant, "where": where, "reason": reason}, ts=ts)


def note_drain(node: str, source: str, reason: str,
               ts: "Optional[float]" = None,
               detail: "Optional[dict]" = None) -> "Optional[str]":
    """One node drain cause into the ring (the interruption controller and
    spot/rebalance.py cite reasons.DRAIN_REASONS literals — lint-enforced;
    the spot-storm drill audits reactive vs proactive attribution)."""
    if not state.enabled():
        return None
    rec = {"node": node, "source": source, "reason": reason}
    if detail:
        rec.update(detail)
    return DECISIONS.emit("drain", rec, ts=ts)
