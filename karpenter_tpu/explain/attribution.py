"""Vectorized mask attribution: WHY is this pod unschedulable, by count.

The dense formulation folds admission into per-dimension mask factors
(taints ∧ requirements ∧ fresh-node fit ∧ availability over the
provisioner × type × slot option lattice — solver/core.py
MASK_DIMENSIONS). Attribution replays that fold for ONE pod and counts,
per dimension, how many candidate options each factor zeroed FIRST (the
encoder's rejection order), then reduces to a ranked reason summary
("897 of 4824 candidates rejected by resources; nearest fit short by
1.2 cores (cpu)").

The pass is lazy/on-demand only — it runs per unassigned pod after a
solve (or from the explain CLI), never on the solve hot path — and it
walks the SAME stages in the SAME order as the scalar oracle's
diagnose_unschedulable (models/encode.py), so the dominant clause is
string-identical to the oracle's verdict by construction; the parity
audit (tests/test_explain.py, benchmarks/explain_drill.py) enforces it
with ``==``. Cost is O(Pv · T · S) numpy over the shared grid arrays;
callers diagnosing many pods per cycle pass `grid`/`kubelet` in once,
exactly like provisioning's event diagnosis.
"""
from __future__ import annotations

import time
from typing import Optional, Sequence

import numpy as np

from ..apis import wellknown as wk
from ..models.encode import (INT_BIG, build_grid, fold_option_mask,
                             kubelet_arrays)
from ..models.pod import PodGroup, PodSpec, tolerates_all
from ..models.requirements import IncompatibleError
from .reasons import CLAUSE_OF, DIMENSIONS
from .records import DECISIONS


def _fmt_deficit(resource: str, raw: float) -> str:
    """Axis units -> operator units (cpu millicores -> cores, memory MiB,
    ephemeral GiB, counts as-is)."""
    if resource == wk.RESOURCE_CPU:
        return f"{raw / 1000:g} cores ({resource})"
    if resource == wk.RESOURCE_MEMORY:
        return f"{raw:g} MiB ({resource})"
    if resource == wk.RESOURCE_EPHEMERAL:
        return f"{raw:g} GiB ({resource})"
    return f"{raw:g} {resource}"


def attribute_pod(
    pod: PodSpec,
    provisioners: "Sequence",
    catalog,
    daemon_overhead: "Optional[Sequence[int]]" = None,
    grid=None,
    kubelet: "Optional[tuple]" = None,
    option_mask: "Optional[np.ndarray]" = None,
) -> dict:
    """Per-dimension rejection counts + ranked summary for one pod.

    Returns ``{"dimension", "reason", "summary", "candidates", "counts",
    "nearest", "provisioners"}`` where ``reason`` is the scalar oracle's
    verbatim clause for the dominant dimension (parity-audited)."""
    t0 = time.perf_counter()
    if grid is None or grid.seqnum != catalog.seqnum:
        grid = build_grid(catalog, reuse=grid)
    provs = list(provisioners)
    cols = grid.get_cols()
    overhead = list(daemon_overhead or [0] * wk.NUM_RESOURCES)
    group = PodGroup(spec=pod, count=1, pod_names=[pod.name])
    vec64 = np.minimum(group.vector, INT_BIG).astype(np.int64)
    ovh = np.asarray(overhead, dtype=np.int64)
    alloc64 = grid.alloc_t.astype(np.int64)
    avail_flat = grid.valid.reshape(-1)
    prov_overhead, prov_pods_cap = (
        kubelet if kubelet is not None else kubelet_arrays(provs, catalog))
    T, S = grid.T, grid.S
    n_defined = int(cols.flat_valid.sum())
    pods_i = wk.RESOURCE_INDEX[wk.RESOURCE_PODS]

    counts = {dim: 0 for dim in DIMENSIONS}
    any_tol = any_req = any_fit = any_avail = any_divers = False
    # the spot plane's diversity-floor mask joins the fold after
    # availability (solver/core.py MASK_DIMENSIONS order); None means the
    # dimension zeroes nothing and the walk is bit-identical to before
    divers_flat = (avail_flat if option_mask is None
                   else (avail_flat & option_mask.reshape(-1)))
    nearest: "Optional[dict]" = None
    for pi, prov in enumerate(provs):
        if not tolerates_all(pod.tolerations, prov.taints):
            counts["taints"] += n_defined
            continue
        any_tol = True
        try:
            reqs = prov.scheduling_requirements().union(pod.requirements)
        except IncompatibleError:
            counts["requirements"] += n_defined
            continue
        req_mask = fold_option_mask(reqs, cols, prov)
        n_req = int(req_mask.sum())
        counts["requirements"] += n_defined - n_req
        if not n_req:
            continue
        any_req = True
        ovh_p = ovh if prov_overhead is None \
            else ovh + prov_overhead[pi].astype(np.int64)
        fits_t = np.all(alloc64 - ovh_p[None, :] - vec64[None, :] >= 0,
                        axis=1)
        if prov_pods_cap is not None:
            fits_t &= (prov_pods_cap[pi].astype(np.int64)
                       - ovh_p[pods_i] - vec64[pods_i] >= 0)
        m1 = req_mask & np.repeat(fits_t, S)
        n_fit = int(m1.sum())
        counts["resources"] += n_req - n_fit
        # nearest-fit shortfall over the types this prov's requirement fold
        # admits but whose allocatable the pod doesn't fit
        fail_t = req_mask.reshape(T, S).any(axis=1) & ~fits_t
        if fail_t.any():
            deficits = (vec64[None, :] + ovh_p[None, :]
                        - alloc64[fail_t]).astype(np.float64)
            rel = deficits / np.maximum(alloc64[fail_t], 1)
            scores = rel.max(axis=1)
            k = int(scores.argmin())
            if nearest is None or scores[k] < nearest["_score"]:
                ri = int(rel[k].argmax())
                nearest = {
                    "_score": float(scores[k]),
                    "resource": wk.RESOURCE_AXIS[ri],
                    "short_by": float(max(deficits[k, ri], 0.0)),
                    "display": _fmt_deficit(
                        wk.RESOURCE_AXIS[ri], max(deficits[k, ri], 0.0)),
                }
        if not n_fit:
            continue
        any_fit = True
        m2 = m1 & avail_flat
        n_avail = int(m2.sum())
        counts["availability"] += n_fit - n_avail
        m3 = m1 & divers_flat
        n_divers = int(m3.sum())
        counts["diversity"] += n_avail - n_divers
        counts["constraints"] += n_divers
        if n_avail:
            any_avail = True
        if n_divers:
            any_divers = True

    # dominant clause: the exact stage walk diagnose_unschedulable does —
    # first stage no provisioner survives
    if not any_tol:
        dim = "taints"
    elif not any_req:
        dim = "requirements"
    elif not any_fit:
        dim = "resources"
    elif not any_avail:
        dim = "availability"
    elif not any_divers:
        dim = "diversity"
    else:
        dim = "constraints"
    total = n_defined * len(provs)
    ranked = sorted(DIMENSIONS, key=lambda d: (-counts[d],
                                               DIMENSIONS.index(d)))
    if dim == "constraints":
        summary = (f"{counts['constraints']} of {total} candidates "
                   f"admissible but blocked by cross-pod constraints "
                   f"(affinity/topology/limits) this cycle")
    else:
        summary = (f"{counts[dim]} of {total} candidates rejected "
                   f"by {dim}")
        if dim == "resources" and nearest is not None:
            summary += f"; nearest fit short by {nearest['display']}"
    if nearest is not None:
        nearest = {k: v for k, v in nearest.items() if k != "_score"}
    out = {
        "dimension": dim,
        "reason": CLAUSE_OF[dim],
        "summary": summary,
        "candidates": total,
        "counts": counts,
        "ranked": ranked,
        "nearest": nearest,
        "provisioners": len(provs),
    }
    DECISIONS.note_attribution(time.perf_counter() - t0, dim)
    return out
