"""Global on/off switch for the decision-provenance (explain) plane.

Like the profiling plane, explain is advisory-never-load-bearing: every
producer — the solve-record emitter, the mask-attribution pass, the
consolidation verdict capture, the fleet shed notes — checks
:func:`enabled` before doing ANY work, so disabling explain is a strict
no-op (zero records, zero ring growth, zero counter movement). The chaos
drill enforces exactly that invariant (``explain-strict-noop``).

Default is ON (decisions exist to be explainable); ``KARPENTER_TPU_EXPLAIN=0``
(or ``false``/``off``/``no``) disables it at process start, and
:func:`set_enabled` / :func:`disabled` flip it at runtime (chaos drills,
overhead baselines).
"""
from __future__ import annotations

import contextlib
import os
import threading

FLAG_ENV = "KARPENTER_TPU_EXPLAIN"
_FALSY = ("0", "false", "off", "no")

_lock = threading.Lock()
_enabled = os.environ.get(FLAG_ENV, "1").strip().lower() not in _FALSY


def enabled() -> bool:
    return _enabled


def set_enabled(on: bool) -> bool:
    """Flip the plane; returns the previous state (restore token)."""
    global _enabled
    with _lock:
        prev = _enabled
        _enabled = bool(on)
        return prev


@contextlib.contextmanager
def disabled():
    """Scoped hard-off: overhead baselines and the chaos strict-noop drill."""
    prev = set_enabled(False)
    try:
        yield
    finally:
        set_enabled(prev)
