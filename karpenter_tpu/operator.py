"""Operator: assembles and runs the whole controller plane.

Parity target: /root/reference/cmd/controller/main.go:33-65 + core
operator.NewOperator — manager wiring, leader election with an `Elected()`
async-start channel (deferred cache hydration, launchtemplate.go:76-85),
healthz registry, settings injection, controller registration and Start().
"""

from __future__ import annotations

import logging
import os
import threading
from typing import Optional

from .apis.settings import Settings
from .cloudprovider import CloudProvider
from .controllers.deprovisioning import DeprovisioningController
from .controllers.counters import CountersController
from .controllers.garbagecollection import GarbageCollectionController
from .controllers.interruption import FakeQueue, InterruptionController
from .controllers.machinehydration import MachineHydrationController
from .controllers.machinelifecycle import MachineLifecycleController
from .controllers.settingswatch import SettingsWatchController
from .controllers.nodetemplate import NodeTemplateController
from .controllers.provisioning import ProvisioningController
from .controllers.termination import TerminationController
from .events import EventRecorder
from .introspect import FlightRecorder, Watchdog
from .leaderelection import LeaderElector
from .metrics import REGISTRY, decorate_cloudprovider
from .recovery import IntentJournal, RecoveryManager
from .resilience import ResilienceHub
from .models.cluster import ClusterState
from .models.instancetype import Catalog
from .fake.kube import FencedKube, KubeStore
from .utils.clock import Clock
from .webhooks import Webhooks

log = logging.getLogger("karpenter.operator")


class Operator:
    # Reconcile cadence per controller: start() drives the background loops
    # from this table and the watchdog derives its deadman thresholds from
    # it (provisioning/interruption run their own watch/long-poll threads —
    # their entries reflect the loop's idle tick, not a timer).
    LOOP_INTERVALS = {
        "provisioning": 0.1,
        "machinelifecycle": 0.2,
        "settingswatch": 2.0,
        "termination": 0.2,
        "deprovisioning": 2.0,
        "nodetemplate": 5.0,
        "machinehydration": 5.0,
        "garbagecollection": 60.0,
        "counters": 5.0,
        "interruption": 1.2,
        "spotrebalance": 2.0,
    }
    # introspection cadence: deadman sweep + flight-recorder snapshot ring
    WATCHDOG_CHECK_INTERVAL = 1.0
    SNAPSHOT_INTERVAL = 10.0
    # SLO burn-rate evaluation tick (introspect/slo.py)
    SLO_INTERVAL = 5.0

    def __init__(self, cloud, settings: Settings, catalog: Catalog,
                 kube: Optional[KubeStore] = None,
                 clock: Optional[Clock] = None,
                 queue=None, solver_factory=None, solver_target: str = "",
                 leader_elect: bool = False,
                 identity: Optional[str] = None,
                 serve_http: bool = False,
                 metrics_port: int = 0, health_port: int = 0,
                 webhook_port: int = 0,
                 webhook_tls: "tuple[str, str]" = ("", "")):
        settings.validate()
        # recent-log ring from boot (served at /logz for the `logs` CLI)
        from .utils import logring

        logring.install()
        self.settings = settings
        self.clock = clock or Clock()
        self.kube = kube or KubeStore()
        self.cluster = ClusterState()
        self.recorder = EventRecorder(clock=self.clock,
                                      sink=self._persist_event)
        # bounded Event-object retention in the coordination plane
        import collections
        import uuid as _uuid

        self._event_names = collections.deque()
        self._event_seq = 0
        self._event_suffix = _uuid.uuid4().hex[:5]  # HA replicas can't collide
        self._event_lock = threading.Lock()  # recorder is shared by 7 threads
        # resilience plane: ONE hub per operator — breakers/budgets/ladders
        # are shared by every call path touching the same dependency
        # (docs/designs/resilience.md)
        self.resilience = ResilienceHub(clock=self.clock,
                                        recorder=self.recorder)
        set_res = getattr(self.kube, "set_resilience", None)
        if callable(set_res):
            set_res(self.resilience.policy("kube"))
        self.cloudprovider = CloudProvider(cloud, settings, catalog,
                                           clock=self.clock,
                                           resilience=self.resilience)
        self.metrics_cloudprovider = decorate_cloudprovider(self.cloudprovider)
        # introspection plane: deadman watchdog on the injected clock; every
        # controller below takes it and wraps its reconcile cycle
        self.watchdog = Watchdog(clock=self.clock, recorder=self.recorder)
        # Leader election (main.go:42 LEADER_ELECT, charts 2-replica/PDB):
        # when enabled, a store-backed lease elects exactly one active
        # replica; controllers idle on standbys and take over within the
        # lease TTL. Single-process mode keeps the bare always-set event.
        self.leader_elect = leader_elect
        if leader_elect:
            import uuid

            self.leader = LeaderElector(
                self.kube, identity or f"karpenter-{uuid.uuid4().hex[:8]}",
                clock=self.clock,
                on_started_leading=self._on_started_leading)
            self.elected = self.leader.elected
            # Fencing: every kube mutation from THIS replica presents its
            # lease epoch, so a deposed-but-unaware ex-leader's late writes
            # are rejected by the store (fake/kube.py Fenced). The elector
            # itself keeps the raw store — its lease writes are what MINT
            # the new epochs.
            if callable(getattr(self.kube, "fence_epoch", None)):
                self.kube = FencedKube(self.kube, self.leader.fencing_token)
        else:
            self.leader = None
            self.elected = threading.Event()
        self._stop = threading.Event()
        self._threads: "list[threading.Thread]" = []
        # HTTP serving plane (metrics/health/webhook — values.yaml:134-142
        # port wiring); port 0 binds ephemerally (tests), opt-in via the CLI
        self.serving = None
        if serve_http:
            from .serving import ServingPlane

            self.serving = ServingPlane(self, metrics_port=metrics_port,
                                        health_port=health_port,
                                        webhook_port=webhook_port,
                                        tls_cert=webhook_tls[0] or None,
                                        tls_key=webhook_tls[1] or None)

        # durable intent journal: write-ahead records for in-flight actions,
        # stamped with this incarnation's epoch (minted by RecoveryManager
        # at leadership/boot — the lambda reads it lazily)
        self.journal = IntentJournal(self.kube, clock=self.clock,
                                     epoch_fn=lambda: self.recovery.epoch)
        self.provisioning = ProvisioningController(
            self.kube, self.cloudprovider, self.cluster, settings,
            clock=self.clock, recorder=self.recorder,
            solver_factory=solver_factory, watchdog=self.watchdog,
            resilience=self.resilience, journal=self.journal)
        self.termination = TerminationController(
            self.kube, self.cloudprovider, self.cluster,
            clock=self.clock, recorder=self.recorder,
            watchdog=self.watchdog, journal=self.journal)
        remote_consolidator = None
        if solver_target:
            # deployed split (SURVEY 7.1): the sidecar owns the chip, so
            # the batched consolidation search runs THERE; the in-process
            # kernel stays the fallback chain's next link. The client is
            # cached per (catalog object+seqnum, provisioner hash) so
            # steady-state cycles reuse the synced session instead of
            # re-shipping the catalog every reconcile (the provisioning
            # path's content-keyed cache discipline).
            _rc_cache: "dict[tuple, object]" = {}

            def remote_consolidator(cluster, catalog, provisioners,
                                    eligible_names, now,
                                    _target=solver_target,
                                    _hub=self.resilience):
                from .solver import wire
                from .solver.client import RemoteSolver

                key = (id(catalog), catalog.seqnum,
                       wire.provisioners_hash(provisioners))
                rs = _rc_cache.get(key)
                if rs is None:
                    _rc_cache.clear()  # one live entry; catalogs don't coexist
                    rs = _rc_cache[key] = RemoteSolver(
                        catalog, provisioners, target=_target,
                        resilience=_hub)
                return rs.consolidate(cluster, eligible_names, now=now)
        self.deprovisioning = DeprovisioningController(
            self.kube, self.cloudprovider, self.cluster, self.termination,
            clock=self.clock, recorder=self.recorder,
            provisioning=self.provisioning,
            remote_consolidator=remote_consolidator,
            watchdog=self.watchdog, resilience=self.resilience,
            journal=self.journal)
        self.nodetemplate = NodeTemplateController(
            self.kube, self.cloudprovider.subnets,
            self.cloudprovider.security_groups, clock=self.clock,
            watchdog=self.watchdog)
        # the kube store is the single source of truth for templates: deletes
        # take effect immediately and no side-registry can drift
        self.cloudprovider.template_source = (
            lambda name: self.kube.get("nodetemplates", name))
        # PDBs flow kube -> cluster state via watch (single write path; the
        # deprovisioner/termination read cluster.pdbs)
        self.kube.watch(self._on_watch_event)
        self.cluster.pdbs = self.kube.pdbs()
        # admission webhooks at the coordination-plane boundary
        # (operator.WithWebhooks analogue, cmd/controller/main.go:58-63)
        self.webhooks = Webhooks(cluster_name=settings.cluster_name)
        self.kube.set_admission(self.webhooks.admit)
        self.machinehydration = MachineHydrationController(
            self.kube, self.cloudprovider, cluster=self.cluster,
            clock=self.clock, watchdog=self.watchdog)
        self.machinelifecycle = MachineLifecycleController(
            self.kube, self.cloudprovider, self.cluster, clock=self.clock,
            watchdog=self.watchdog)
        self.settingswatch = SettingsWatchController(
            self.kube, settings, clock=self.clock, watchdog=self.watchdog)
        self.garbagecollection = GarbageCollectionController(
            self.kube, self.cloudprovider, clock=self.clock,
            cluster=self.cluster, termination=self.termination,
            watchdog=self.watchdog)
        self.counters = CountersController(self.kube, self.cluster,
                                           watchdog=self.watchdog)
        self.interruption = None
        if settings.interruption_queue_name:
            self.queue = queue or FakeQueue(settings.interruption_queue_name,
                                            clock=self.clock)
            self.interruption = InterruptionController(
                self.kube, self.cluster, self.queue, self.cloudprovider.ice,
                termination=self.termination, clock=self.clock,
                recorder=self.recorder, watchdog=self.watchdog)
        # spot-storm resilience plane (spot/): interruption forecasts feed
        # a risk-aware solve objective (injected into provisioning) and a
        # proactive rebalance controller. Advisory — strict noop under
        # KARPENTER_TPU_SPOT=0, and inert at the static forecast baseline.
        from .spot import RebalanceController, RiskObjective, SpotForecaster

        self.spotforecaster = SpotForecaster(clock=self.clock,
                                             recorder=self.recorder)
        self.spotobjective = RiskObjective(self.spotforecaster)
        self.provisioning.spot_objective = self.spotobjective
        self.spotrebalance = RebalanceController(
            self.kube, self.cloudprovider, self.cluster, self.termination,
            self.provisioning, self.spotforecaster, clock=self.clock,
            recorder=self.recorder, journal=self.journal,
            watchdog=self.watchdog)
        # deadman thresholds: generous multiples of each loop's interval so
        # a busy-but-alive controller never flaps (floor 120s = the event
        # dedupe TTL); a controller that misses ~10 turns is genuinely stuck
        for ctrl, interval in self.LOOP_INTERVALS.items():
            if ctrl == "interruption" and self.interruption is None:
                continue
            self.watchdog.register(ctrl, threshold=max(120.0, 10 * interval))
        # flight recorder: periodic statusz ring + auto bundles on reconcile
        # exceptions and deadman firings (chaos adds invariant breaches)
        self.flightrecorder = FlightRecorder(
            self, out_dir=os.environ.get("KARPENTER_TPU_BUNDLE_DIR") or None)
        self.watchdog.add_stall_listener(
            lambda names: self.flightrecorder.trigger(
                "watchdog_deadman", detail=", ".join(names)))
        self.watchdog.add_failure_listener(
            lambda name, err: self.flightrecorder.trigger(
                "reconcile_exception",
                detail=f"{name}: {type(err).__name__}: {err}"))
        # perf SLO plane: declarative objectives evaluated from the metric
        # families into karpenter_slo_* gauges with multi-window burn
        # rates; a short-window burn edge-triggers an SloBurn event and a
        # flight-recorder bundle (docs/designs/slo.md)
        from .introspect.slo import SloEvaluator

        self.slo = SloEvaluator(clock=self.clock, recorder=self.recorder,
                                flightrecorder=self.flightrecorder)
        # fleet federation view (/debug/fleetz): this replica is always its
        # own first member; multi-replica deployments add Http/Local
        # replicas (and a FleetRouter) as they join
        from .introspect import statusz as _statusz
        from .introspect.fleetview import FleetView, LocalReplica

        self.fleetview = FleetView(name=os.environ.get(
            "KARPENTER_TPU_REPLICA_NAME", "self"), clock=self.clock)
        self.fleetview.add_replica(LocalReplica(
            self.fleetview.name,
            statusz=lambda: _statusz.snapshot(self)))
        # crash-restart recovery: epoch minting + stranded-intent replay on
        # each incarnation (docs/designs/recovery.md)
        self.recovery = RecoveryManager(self)

    def _on_watch_event(self, kind: str, action: str, obj) -> None:
        if kind == "pdbs":
            self.cluster.pdbs = self.kube.pdbs()
        elif kind == "provisioners" and action == "deleted":
            # nodes are OWNED by the provisioner that launched them: its
            # deletion gracefully terminates them (reference
            # deprovisioning.md:22 — the reference gets the cascade from
            # node ownerReferences + kube GC; here the observed deletion
            # drives it, and the GC controller's orphan sweep is the
            # level-triggered backstop for nodes that register after this
            # event or while the controller is down). Standbys receive the
            # same watch event but only the LEADER may write.
            pname = getattr(obj, "name", None)
            term = getattr(self, "termination", None)
            if pname and term is not None and (
                    not self.leader_elect or self.elected.is_set()):
                for nname in sorted(self.cluster.nodes):
                    node = self.cluster.nodes.get(nname)
                    if (node is not None
                            and node.provisioner_name == pname
                            and not node.marked_for_deletion
                            and term.request_deletion(nname)):
                        self.recorder.normal(
                            f"node/{nname}", "OwnerDeleted",
                            f"provisioner {pname} deleted; terminating "
                            "owned node")
        elif kind == "nodes" and action == "modified":
            # kubectl-mutable node surface -> live cluster state: the
            # do-not-consolidate veto (and future annotation knobs) must
            # reach the deprovisioner's eligibility checks; everything
            # else on StateNode is controller-owned and must NOT be
            # overwritten by a stale store echo
            live = self.cluster.nodes.get(getattr(obj, "name", None))
            if live is not None and live is not obj:
                live.annotations = dict(getattr(obj, "annotations", {}) or {})
        elif kind == "pods" and action in ("added", "modified", "deleted"):
            # bound-pod updates (kubectl annotate do-not-evict, priority
            # edits) and deletions must refresh the OWNING node's resident
            # list — eligibility and drain read node.pods, and the object
            # appended at bind time goes stale the moment the store's copy
            # is replaced (PodSpec is immutable-by-replace).
            # Snapshot-rebuild + ONE attribute reassign: in-process notifies
            # run on the writer's thread, but foreign writes arrive on the
            # watch thread, and index mutation against a concurrently
            # reassigned list (termination's daemons-only rebuild) could
            # delete the wrong element; attribute assignment is atomic.
            node_name = getattr(obj, "node_name", "")
            live = self.cluster.nodes.get(node_name) if node_name else None
            if live is not None:
                pods = live.pods
                if action == "deleted":
                    rebuilt = [p for p in pods if p.name != obj.name]
                elif any(p.name == obj.name for p in pods):
                    rebuilt = [obj if p.name == obj.name else p for p in pods]
                else:
                    # newly BOUND pod (kube-scheduler placing onto an
                    # existing node, or a daemon arriving late): it must
                    # join the resident list or emptiness/eligibility will
                    # judge the node by a stale view (karpenter-core
                    # cluster.updatePod tracks these binds the same way)
                    rebuilt = pods + [obj]
                if rebuilt != pods:
                    live.pods = rebuilt

    MAX_STORED_EVENTS = 2000

    def _persist_event(self, ts: float, event) -> None:
        """Recorded events become Event objects in the coordination plane
        (`kubectl get events` parity); retention is bounded by deleting the
        oldest beyond MAX_STORED_EVENTS. Only the name mint and retention
        bookkeeping are serialized — the recorder is shared across every
        controller thread, and a torn seq would mint colliding names — but
        the store I/O happens OUTSIDE the lock: over HttpKubeStore each
        create is a synchronous apiserver round-trip, and holding the lock
        across it would serialize every event-emitting controller thread
        behind a slow apiserver (ADVICE r3)."""
        with self._event_lock:
            self._event_seq += 1
            name = f"evt-{self._event_suffix}-{self._event_seq:07d}"
            self._event_names.append(name)
            evict = []
            while len(self._event_names) > self.MAX_STORED_EVENTS:
                evict.append(self._event_names.popleft())
        try:
            self.kube.create("events", name, {
                "name": name, "ts": ts, "kind": event.kind,
                "reason": event.reason, "object_ref": event.object_ref,
                "message": event.message})
        finally:
            # evicted names left the deque above; delete them even when the
            # create blips, else they leak until a restart's prune sweep
            for old in evict:
                try:
                    self.kube.delete("events", old)
                except Exception as e:
                    log.warning("event retention delete %s failed: %s", old, e)

    def _prune_stored_events(self) -> None:
        """Crash-restart hygiene: a replica that died left its evt-* objects
        behind with no process-local retention state. On start, cap the
        store-wide population at MAX_STORED_EVENTS, oldest first (stored
        events carry their own name for exactly this sweep)."""
        try:
            stored = sorted(
                (o.get("ts", 0.0), o["name"])
                for o in self.kube.list("events")
                if isinstance(o, dict) and o.get("name"))
        except Exception as e:
            log.warning("event prune skipped: %s", e)
            return
        for _, name in stored[:max(0, len(stored) - self.MAX_STORED_EVENTS)]:
            self.kube.delete("events", name)

    # -- lifecycle -------------------------------------------------------------

    def _on_started_leading(self) -> None:
        # leader-gated hydration (launchtemplate.go:76-85): standbys must not
        # prefetch against the leader's cache-eviction discipline
        try:
            self.cloudprovider.launch_templates.hydrate()
        except Exception as e:
            log.warning("leader hydration failed: %s", e)
        # recovery replay before the first reconcile cycles: mint this
        # life's epoch (the lease's fencing token), rebuild cluster state
        # from the surviving stores (roll-forward/back decisions read it),
        # then resolve whatever the previous leader left in the journal
        try:
            self.recovery.begin_incarnation()
            self.machinehydration.reconcile_once()
            self.recovery.replay()
        except Exception as e:
            log.warning("recovery replay at leadership start failed: %s", e)

    def start(self) -> None:
        """Start background controller loops (operator Start, main.go:64).
        With leader_elect, reconcile loops spin but act only while this
        replica holds the lease (manager-gated controllers analogue)."""
        if self.serving is not None:
            ports = self.serving.start()
            log.info("serving plane up: %s", ports)
        self._prune_stored_events()  # orphans from crashed replicas
        if self.leader is not None:
            t0 = threading.Thread(target=self.leader.run, args=(self._stop,),
                                  name="leaderelection", daemon=True)
            t0.start()
            self._threads.append(t0)
        else:
            self.elected.set()
            # single-process mode hydrates inline and FAILS FAST: a broken
            # cloud API at boot should abort start, not surface per-launch
            self.cloudprovider.launch_templates.hydrate()
            # replay stranded intents from prior incarnations (boot-counter
            # epoch) before any controller loop takes its first turn
            self.recovery.begin_incarnation()
            self.machinehydration.reconcile_once()
            self.recovery.replay()

        def loop(name, fn, interval):
            def run():
                while not self._stop.is_set():
                    if self.elected.is_set():
                        try:
                            fn()
                        except Exception as e:
                            log.exception("%s failed: %s", name, e)
                    self._stop.wait(interval)

            t = threading.Thread(target=run, name=name, daemon=True)
            t.start()
            self._threads.append(t)

        t = threading.Thread(target=self.provisioning.run,
                             args=(self._stop, self.elected),
                             name="provisioning", daemon=True)
        t.start()
        self._threads.append(t)
        iv = self.LOOP_INTERVALS
        loop("machinelifecycle", self.machinelifecycle.reconcile_once,
             iv["machinelifecycle"])
        loop("settingswatch", self.settingswatch.reconcile_once,
             iv["settingswatch"])
        loop("termination", self.termination.reconcile_once,
             iv["termination"])
        loop("deprovisioning", self.deprovisioning.reconcile_once,
             iv["deprovisioning"])
        loop("nodetemplate", self.nodetemplate.reconcile_once,
             iv["nodetemplate"])
        loop("machinehydration", self.machinehydration.reconcile_once,
             iv["machinehydration"])
        loop("garbagecollection", self.garbagecollection.reconcile_once,
             iv["garbagecollection"])
        loop("counters", self.counters.reconcile_once, iv["counters"])
        loop("spotrebalance", self._spot_tick, iv["spotrebalance"])
        if self.interruption is not None:
            t2 = threading.Thread(target=self.interruption.run,
                                  args=(self._stop, self.elected),
                                  name="interruption", daemon=True)
            t2.start()
            self._threads.append(t2)
        # introspection loops: the deadman sweep (feeds /readyz, the healthy
        # gauges and stall/recovery events) and the flight recorder's
        # periodic statusz ring
        loop("watchdog", self.watchdog.check, self.WATCHDOG_CHECK_INTERVAL)
        loop("flightrecorder", self.flightrecorder.record_snapshot,
             self.SNAPSHOT_INTERVAL)
        loop("slo", self.slo.evaluate, self.SLO_INTERVAL)

    def stop(self) -> None:
        # The graceful lease release happens inside the election thread's
        # run() exit path — releasing from THIS thread would race an
        # in-flight renewal tick and could leave the lease dangling (or
        # resurrect it mid-shutdown). stop_event wakes the elector's wait
        # immediately, so the handoff is still prompt.
        self._stop.set()
        if self.serving is not None:
            self.serving.stop()
        for t in self._threads:
            t.join(timeout=2)
        self.kube.unwatch(self._on_watch_event)  # shared-store replicas must not
        # leak dead watchers across restarts (multi-replica HA mode)
        self.provisioning.stop()
        if self.interruption is not None:
            self.interruption.stop()
        self.cloudprovider.stop()

    # -- health ----------------------------------------------------------------

    def healthz(self) -> bool:
        return True

    def readyz(self) -> "tuple[bool, str]":
        """Watchdog-aggregated readiness: (ready, detail). Standby replicas
        report ready — their controllers idle by design, and an unready
        standby would be restarted by its probe right when it matters."""
        if self.leader_elect and not self.elected.is_set():
            return True, "ok (standby)"
        # open breakers are degradation, not death: readiness stays true
        # (restarting the pod wouldn't heal the dependency) but the detail
        # names them so the probe's failure story is one curl away
        open_brk = self.resilience.open_breakers()
        brk = f" (breakers open: {', '.join(open_brk)})" if open_brk else ""
        stalled = self.watchdog.check()
        if stalled:
            return False, ("unhealthy: stalled controllers: "
                           + ", ".join(stalled) + brk)
        return True, "ok" + brk

    def livez(self) -> bool:
        return self.cloudprovider.livez()

    def metrics_text(self) -> str:
        return REGISTRY.expose()

    # -- synchronous drive (tests / single-shot CLI) ----------------------------

    def _spot_tick(self) -> None:
        """One spot-plane turn: refresh the interruption forecast, then
        give the proactive rebalance controller a cycle. Both are strict
        noops while KARPENTER_TPU_SPOT=0."""
        self.spotforecaster.refresh()
        self.spotrebalance.reconcile_once()

    def reconcile_all_once(self) -> None:
        """One deterministic pass over every controller (hermetic tests)."""
        self.settingswatch.reconcile_once()
        self.nodetemplate.reconcile_once()
        self.machinehydration.reconcile_once()
        self.provisioning.reconcile_once()
        self.machinelifecycle.reconcile_once()
        if self.interruption is not None:
            self.interruption.reconcile_once()
        self._spot_tick()
        self.deprovisioning.reconcile_once()
        self.termination.reconcile_once()
        self.counters.reconcile_once()
