"""Dependency-free span tracing (same zero-deps stance as metrics/).

The scheduling cycle is really a routing decision between a ~7ms native
path and a ~129ms on-chip path with poorly understood state transitions
(BENCH_r05 link_state.after_first_read: 0.05ms -> 68ms); duration
histograms alone cannot attribute a slow cycle to its phase. This module
adds the missing causal layer: spans with parent links, attributes, a
thread-safe bounded ring buffer of finished spans, and Chrome
`trace_event` JSON export loadable in Perfetto / chrome://tracing.

Span taxonomy (names double as the `phase` label of the
karpenter_scheduling_phase_duration_seconds histogram):

  provisioning.cycle            root, one per reconcile_once
    provisioning.mask           constraint-mask build (catalog/zones/overhead)
    provisioning.solve          routed solve; attrs: routing, pods,
                                compile_cache, transfer_ms
    provisioning.bind           launch + bind (_apply)
  deprovisioning.cycle          root, one per reconcile_once
    deprovisioning.<mechanism>  emptiness | expiration | drift | consolidation
  solver.rpc.<Method>           client side of the wire (RemoteSolver)
  solver.service.<Method>       sidecar side; joins the caller's trace via
                                the wire trace_context field
  solver.solve                  in-process solver pipeline; attrs:
                                encode_ms, dispatch_ms, transfer_ms,
                                decode_ms, compile_cache

Trace context crosses the solver wire as (trace_id, span_id) strings —
see solver/wire.py trace_context_to_wire / trace_context_from_wire.

Export surfaces: serving.py `/debug/traces` (recent traces as JSON;
`?id=<trace_id>` returns that trace as Chrome trace_event JSON), and the
span-end hook feeding metrics.REGISTRY so Prometheus and traces agree.
"""

from __future__ import annotations

import collections
import json
import os
import threading
import time
from typing import Optional

from ..metrics import NAMESPACE, REGISTRY

PHASE_METRIC = f"{NAMESPACE}_scheduling_phase_duration_seconds"

# finished-span ring capacity: ~200 traces of a dozen spans; bounded so a
# long-lived operator cannot grow without limit (KARPENTER_TPU_TRACE_RING
# overrides for soak tests)
_DEFAULT_RING = 2048

#: Every LITERAL span name the codebase records, in one place. The
#: profiling gap ledger maps its phase table onto entries here, and
#: hack/check_phase_accounting.py (make presubmit) fails the build when a
#: span literal appears in code without appearing below — the drift
#: tripwire that keeps attribution accounting honest. Span families built
#: with f-strings (client RPC methods, deprovisioning mechanisms) are
#: covered by DYNAMIC_PHASE_PREFIXES instead.
PHASE_REGISTRY = (
    "provisioning.cycle",
    "provisioning.mask",
    "provisioning.solve",
    "provisioning.bind",
    "provisioning.bind.existing",
    "provisioning.bind.pods",
    "provisioning.create",
    "deprovisioning.cycle",
    "deprovisioning.emptiness",
    "deprovisioning.expiration",
    "deprovisioning.drift",
    "deprovisioning.consolidation",
    "solver.service.Sync",
    "solver.service.Solve",
    "solver.service.Consolidate",
    "solver.extract",
    "solver.warm_start",
    "solver.encode",
    "solver.serialize",
    "solver.dispatch.execute",
    "solver.dispatch.compile",
    "solver.transfer",
    "solver.decode",
    "ingest.decode",
    "ingest.apply",
    "fleet.queue_wait",
)

#: prefixes legitimising dynamically-built span names (f-strings)
DYNAMIC_PHASE_PREFIXES = (
    "solver.rpc.",
    "deprovisioning.",
)


def _new_id(nbytes: int = 8) -> str:
    return os.urandom(nbytes).hex()


class SpanContext:
    """The propagatable identity of a span: (trace_id, span_id). This is
    what crosses the solver wire; everything else stays process-local."""

    __slots__ = ("trace_id", "span_id")

    def __init__(self, trace_id: str, span_id: str):
        self.trace_id = trace_id
        self.span_id = span_id

    def __bool__(self) -> bool:
        return bool(self.trace_id)

    def __repr__(self):
        return f"SpanContext(trace_id={self.trace_id!r}, span_id={self.span_id!r})"


class Span:
    """One timed operation. Created by Tracer.start_span; usable as a
    context manager (ends on exit, exceptions recorded as error=True)."""

    def __init__(self, tracer: "Tracer", name: str, trace_id: str,
                 span_id: str, parent_id: str, attributes: dict):
        self.tracer = tracer
        self.name = name
        self.trace_id = trace_id
        self.span_id = span_id
        self.parent_id = parent_id
        self.attributes = dict(attributes)
        self.start_ts = time.time()
        self._t0 = time.perf_counter()
        self.duration_s: "Optional[float]" = None
        self.thread_id = threading.get_ident()
        self.thread_name = threading.current_thread().name

    def context(self) -> SpanContext:
        return SpanContext(self.trace_id, self.span_id)

    def set_attribute(self, key: str, value) -> "Span":
        self.attributes[key] = value
        return self

    def set_attributes(self, **attrs) -> "Span":
        self.attributes.update(attrs)
        return self

    def end(self) -> None:
        if self.duration_s is not None:  # idempotent: double-end is a no-op
            return
        self.duration_s = time.perf_counter() - self._t0
        self.tracer._finish(self)

    def __enter__(self) -> "Span":
        return self

    def __exit__(self, exc_type, exc, tb):
        if exc_type is not None:
            self.attributes["error"] = True
            self.attributes.setdefault("error.type", exc_type.__name__)
        self.end()
        return False

    def to_dict(self) -> dict:
        return {
            "name": self.name,
            "trace_id": self.trace_id,
            "span_id": self.span_id,
            "parent_id": self.parent_id,
            "start_ts": self.start_ts,
            "duration_ms": (self.duration_s or 0.0) * 1e3,
            "attributes": self.attributes,
            "thread": self.thread_name,
        }


class Tracer:
    """Span factory + bounded recorder.

    Parenting: an explicit `parent` Span (or remote SpanContext via
    `context=`) wins; otherwise the thread-local current span — so
    controller code only names the root and children nest themselves.
    Finished spans land in a ring buffer (deque maxlen) under a lock;
    the span-end hook observes duration into the phase histogram.
    """

    def __init__(self, ring_size: "Optional[int]" = None,
                 registry=REGISTRY):
        if ring_size is None:
            try:
                ring_size = int(os.environ.get(
                    "KARPENTER_TPU_TRACE_RING", _DEFAULT_RING))
            except ValueError:
                ring_size = _DEFAULT_RING
        self._lock = threading.Lock()
        self._finished: "collections.deque[Span]" = collections.deque(
            maxlen=max(1, ring_size))
        self._tls = threading.local()
        self._phase_hist = registry.histogram(
            PHASE_METRIC,
            "Duration of scheduling phases, recorded from tracing spans.",
            ("phase",)) if registry is not None else None

    # -- span lifecycle ------------------------------------------------------

    def _stack(self) -> list:
        st = getattr(self._tls, "stack", None)
        if st is None:
            st = self._tls.stack = []
        return st

    def current_span(self) -> "Optional[Span]":
        st = self._stack()
        return st[-1] if st else None

    def start_span(self, name: str, parent: "Optional[Span]" = None,
                   context: "Optional[SpanContext]" = None,
                   **attributes) -> Span:
        """Open a span. Resolution of the parent link: explicit `parent`
        span > remote `context` (joins that trace) > thread-local current
        span > new root."""
        if parent is not None:
            trace_id, parent_id = parent.trace_id, parent.span_id
        elif context is not None and context.trace_id:
            trace_id, parent_id = context.trace_id, context.span_id
        else:
            cur = self.current_span()
            if cur is not None:
                trace_id, parent_id = cur.trace_id, cur.span_id
            else:
                trace_id, parent_id = _new_id(), ""
        span = Span(self, name, trace_id, _new_id(), parent_id, attributes)
        self._stack().append(span)
        return span

    def annotate(self, **attrs) -> None:
        """Attach attributes to the current span, if any (deep layers —
        solver core, ops kernels — annotate without plumbing a span)."""
        cur = self.current_span()
        if cur is not None:
            cur.attributes.update(attrs)

    def record_span(self, name: str, duration_s: float,
                    parent: "Optional[Span]" = None,
                    context: "Optional[SpanContext]" = None,
                    **attributes) -> Span:
        """Synthesize an already-measured span (backdated by `duration_s`).
        Hot paths that time phases with raw perf_counter deltas — solver
        encode/dispatch, fleet queue wait, watch-ingest batches — file
        those measurements as first-class spans without paying a context
        manager per inner iteration. Parent resolution matches
        start_span; the span never touches the thread-local stack."""
        if parent is not None:
            trace_id, parent_id = parent.trace_id, parent.span_id
        elif context is not None and context.trace_id:
            trace_id, parent_id = context.trace_id, context.span_id
        else:
            cur = self.current_span()
            if cur is not None:
                trace_id, parent_id = cur.trace_id, cur.span_id
            else:
                trace_id, parent_id = _new_id(), ""
        span = Span(self, name, trace_id, _new_id(), parent_id, attributes)
        span.start_ts -= duration_s
        span.duration_s = max(0.0, duration_s)
        self._finish(span)
        return span

    def _finish(self, span: Span) -> None:
        st = self._stack()
        if span in st:  # tolerate out-of-order ends from with-blocks
            st.remove(span)
        with self._lock:
            self._finished.append(span)
        if self._phase_hist is not None:
            # the trace id rides along as the series exemplar, so a slow
            # histogram percentile resolves to a concrete trace
            self._phase_hist.observe(span.duration_s,
                                     exemplar=span.trace_id, phase=span.name)

    # -- read side -----------------------------------------------------------

    def finished_spans(self) -> "list[Span]":
        with self._lock:
            return list(self._finished)

    def phase_sum(self, phase: str) -> float:
        """Cumulative seconds observed for one phase; benchmarks read
        deltas of this around a measured window to attribute wall clock."""
        return (self._phase_hist.sum(phase=phase)
                if self._phase_hist is not None else 0.0)

    def trace(self, trace_id: str) -> "list[dict]":
        return [s.to_dict() for s in self.finished_spans()
                if s.trace_id == trace_id]

    def traces(self, limit: int = 20) -> "list[dict]":
        """Most recent `limit` traces, newest first, each with its spans in
        start order and a root-derived summary line."""
        by_trace: "dict[str, list[Span]]" = {}
        order: "list[str]" = []
        for s in self.finished_spans():
            if s.trace_id not in by_trace:
                order.append(s.trace_id)
                by_trace[s.trace_id] = []
            by_trace[s.trace_id].append(s)
        out = []
        for tid in reversed(order[-limit:] if limit else order):
            spans = sorted(by_trace[tid], key=lambda s: s.start_ts)
            roots = [s for s in spans if not s.parent_id]
            root = roots[0] if roots else spans[0]
            out.append({
                "trace_id": tid,
                "root": root.name,
                "start_ts": root.start_ts,
                "duration_ms": (root.duration_s or 0.0) * 1e3,
                "n_spans": len(spans),
                "spans": [s.to_dict() for s in spans],
            })
        return out

    def trace_index(self, limit: int = 20) -> "list[dict]":
        """The `/debug/traces` index: the most recent `limit` trace ids,
        newest first, WITHOUT span bodies — just what a triage needs to
        pick an id: root span name, duration, span count, and the tenant/
        replica annotations found anywhere in the trace (the fleet files
        `tenant` on queue-wait and Solve spans, federation files
        `replica`)."""
        out = []
        for t in self.traces(limit):
            tenants: "set[str]" = set()
            replicas: "set[str]" = set()
            for s in t["spans"]:
                attrs = s.get("attributes", {})
                if attrs.get("tenant"):
                    tenants.add(str(attrs["tenant"]))
                if attrs.get("replica"):
                    replicas.add(str(attrs["replica"]))
            out.append({
                "trace_id": t["trace_id"],
                "root": t["root"],
                "start_ts": t["start_ts"],
                "duration_ms": t["duration_ms"],
                "n_spans": t["n_spans"],
                "tenants": sorted(tenants),
                "replicas": sorted(replicas),
            })
        return out

    def chrome_trace(self, trace_id: "Optional[str]" = None) -> dict:
        """Chrome trace_event JSON (the Perfetto / chrome://tracing
        format): complete ("X") events, µs timestamps, one pid, tid =
        recording thread."""
        events = []
        pid = os.getpid()
        for s in self.finished_spans():
            if trace_id is not None and s.trace_id != trace_id:
                continue
            events.append({
                "name": s.name,
                "cat": s.trace_id,
                "ph": "X",
                "ts": s.start_ts * 1e6,
                "dur": (s.duration_s or 0.0) * 1e6,
                "pid": pid,
                "tid": s.thread_id,
                "args": {k: v for k, v in s.attributes.items()},
            })
        events.sort(key=lambda e: e["ts"])
        return {"traceEvents": events, "displayTimeUnit": "ms"}

    def chrome_trace_json(self, trace_id: "Optional[str]" = None) -> str:
        return json.dumps(self.chrome_trace(trace_id), default=str)

    def phase_coverage(self, trace_id: "Optional[str]" = None,
                       root_name: str = "provisioning.cycle") -> "Optional[dict]":
        """How much of a root span's wall clock its direct children account
        for. The SLO plane's attribution invariant (docs/designs/slo.md):
        if coverage drops below ~95%, someone added work to the cycle
        outside any phase span, and a cycle-latency burn can no longer be
        attributed. Picks the newest finished trace containing `root_name`
        when `trace_id` is not given."""
        spans = self.finished_spans()
        if trace_id is None:
            for s in reversed(spans):
                if s.name == root_name and not s.parent_id:
                    trace_id = s.trace_id
                    break
            if trace_id is None:
                return None
        in_trace = [s for s in spans if s.trace_id == trace_id]
        roots = [s for s in in_trace
                 if s.name == root_name or not s.parent_id]
        if not roots or not in_trace:
            return None
        root = roots[0]
        children = [s for s in in_trace if s.parent_id == root.span_id]
        root_s = root.duration_s or 0.0
        covered_s = sum(s.duration_s or 0.0 for s in children)
        return {
            "trace_id": trace_id,
            "root": root.name,
            "root_s": root_s,
            "covered_s": covered_s,
            "coverage": (min(1.0, covered_s / root_s) if root_s > 0 else 1.0),
            "phases": {s.name: round(s.duration_s or 0.0, 6)
                       for s in children},
        }

    def clear(self) -> None:
        with self._lock:
            self._finished.clear()


TRACER = Tracer()


def start_span(name: str, **kwargs) -> Span:
    return TRACER.start_span(name, **kwargs)


def current_span() -> "Optional[Span]":
    return TRACER.current_span()


def annotate(**attrs) -> None:
    TRACER.annotate(**attrs)
