"""Cross-layer safety invariants, asserted at quiescence.

These are the properties the whole controller plane exists to keep true
no matter what the cloud or the transport did mid-flight (ISSUE 2;
reference deprovisioning/interruption docs). They are checked against
FINAL state — transient violations during convergence are expected and
legal; a violation that survives the settle + GC phases is a real bug.

Each check returns Violation records rather than raising, so one run
reports every broken property at once and the runner can embed them in
the replay artifact.
"""

from __future__ import annotations

import dataclasses

from ..models.machine import parse_provider_id

# prices are catalog floats; replacement-vs-disrupted comparisons must
# tolerate representation error, never real cost regressions
_COST_EPS = 1e-9


@dataclasses.dataclass(frozen=True)
class Violation:
    invariant: str
    message: str

    def as_dict(self) -> dict:
        return {"invariant": self.invariant, "message": self.message}


def _iid_of_machine(machine) -> str:
    pid = machine.status.provider_id
    if not pid:
        return ""
    try:
        return parse_provider_id(pid)[1]
    except ValueError:
        return ""


def check_token_ledger(token_launches: "dict[str, int]") -> "list[Violation]":
    """No client token ever double-launches a fleet (EC2 ClientToken
    semantics; the PR 1 dedupe). The ledger counts INNER launches per
    token at the cloud-API server — a transport retry must replay, not
    relaunch."""
    return [
        Violation("token-single-launch",
                  f"client token {tok!r} launched {n} fleets (expected <=1)")
        for tok, n in sorted(token_launches.items()) if n > 1
    ]


def check_bijection(op, cloud) -> "list[Violation]":
    """Cloud instances <-> machines <-> nodes form a bijection: no leaked
    instance without a machine, no machine without live capacity, no
    cluster node without either, and the kube node objects mirror the
    cluster state."""
    out = []
    with cloud.lock:
        live = {i.id for i in cloud.instances.values()
                if i.state != "terminated"}
    machines = {m.name: _iid_of_machine(m) for m in op.kube.machines()}
    machine_iids = {iid for iid in machines.values() if iid}
    node_iids = {}
    for name, node in sorted(op.cluster.nodes.items()):
        if node.provider_id:
            node_iids[node.provider_id.rsplit("/", 1)[-1]] = name
    for iid in sorted(live - machine_iids):
        out.append(Violation(
            "no-leaked-instances",
            f"cloud instance {iid} is running with no owning machine"))
    for name, iid in sorted(machines.items()):
        if iid and iid not in live:
            out.append(Violation(
                "no-ghost-machines",
                f"machine {name} references terminated/absent instance {iid}"))
    for iid, name in sorted(node_iids.items()):
        if iid not in live:
            out.append(Violation(
                "no-ghost-nodes",
                f"node {name} references terminated/absent instance {iid}"))
    for iid in sorted(live - set(node_iids)):
        out.append(Violation(
            "instance-has-node",
            f"running instance {iid} never registered a cluster node"))
    kube_nodes = {n.name for n in op.kube.nodes()}
    cluster_nodes = set(op.cluster.nodes)
    for name in sorted(kube_nodes ^ cluster_nodes):
        out.append(Violation(
            "store-cluster-node-sync",
            f"node {name} present in only one of kube store / cluster state"))
    return out


def check_binds(op) -> "list[Violation]":
    """Every schedulable (non-daemon) pod binds exactly once: bound to a
    node that exists, resident on exactly that node's pod list, and no
    pod left pending at quiescence."""
    out = []
    residency: "dict[str, list[str]]" = {}
    for nname, node in sorted(op.cluster.nodes.items()):
        for p in node.pods:
            residency.setdefault(p.name, []).append(nname)
    for pod in sorted(op.kube.pods(), key=lambda p: p.name):
        if pod.is_daemon():
            continue
        homes = residency.get(pod.name, [])
        if not pod.node_name:
            out.append(Violation(
                "pod-binds-once",
                f"pod {pod.name} still unbound at quiescence"))
        elif pod.node_name not in op.cluster.nodes:
            out.append(Violation(
                "pod-binds-once",
                f"pod {pod.name} bound to nonexistent node {pod.node_name}"))
        elif len(homes) != 1 or homes[0] != pod.node_name:
            out.append(Violation(
                "pod-binds-once",
                f"pod {pod.name} bound to {pod.node_name} but resident on "
                f"{homes or 'no node'}"))
    return out


def check_termination_terminal(op, cloud) -> "list[Violation]":
    """Terminating machines always reach deleted: at quiescence nothing
    may still be marked for deletion, and every terminated instance's
    machine/node bookkeeping must be gone (covered by the bijection
    checks for the object side)."""
    out = []
    for name, node in sorted(op.cluster.nodes.items()):
        if node.marked_for_deletion:
            out.append(Violation(
                "termination-terminal",
                f"node {name} still marked for deletion at quiescence"))
    from ..models import machine as machine_model

    for m in sorted(op.kube.machines(), key=lambda m: m.name):
        if m.status.state == machine_model.TERMINATING or m.deleted:
            out.append(Violation(
                "termination-terminal",
                f"machine {m.name} stuck in {m.status.state}"))
    return out


def check_consolidation_cost(actions: "list[dict]") -> "list[Violation]":
    """Consolidation never raises fleet cost: a delete always saves; a
    replace's new node must not cost more than the nodes it disrupts.
    Checked per recorded action (mid-flight-safe: a global before/after
    snapshot would misfire while a two-phase replace is in its legal
    both-nodes-up window)."""
    out = []
    for i, a in enumerate(actions):
        disrupted = sum(a["node_prices"].values())
        if a["savings"] < -_COST_EPS:
            out.append(Violation(
                "consolidation-cost",
                f"action #{i} ({a['kind']} {a['nodes']}) claims negative "
                f"savings {a['savings']:.6f}"))
        if a["kind"] == "replace" and a["replacement_price"] is not None:
            if a["replacement_price"] > disrupted + _COST_EPS:
                out.append(Violation(
                    "consolidation-cost",
                    f"action #{i} replaces {a['nodes']} "
                    f"(${disrupted:.4f}/h) with a pricier node "
                    f"(${a['replacement_price']:.4f}/h)"))
    return out


def check_breaker_discipline(resilience: "dict | None") -> "list[Violation]":
    """Breakers open within K consecutive failures: no dependency ever
    accumulates a closed-state failure streak past its threshold without
    tripping, and the transition ledger itself is a well-formed FSM walk
    (every hop departs from the state the previous hop arrived at)."""
    out = []
    for dep, ev in sorted((resilience or {}).get("breakers", {}).items()):
        k = ev["failure_threshold"]
        if ev["max_closed_streak"] > k:
            out.append(Violation(
                "breaker-opens-within-k",
                f"dependency {dep}: {ev['max_closed_streak']} consecutive "
                f"closed-state failures exceeded threshold {k} without the "
                f"breaker opening"))
        state = "closed"
        for t in ev["transitions"]:
            if t["from"] != state:
                out.append(Violation(
                    "breaker-opens-within-k",
                    f"dependency {dep}: transition ledger discontinuity — "
                    f"hop departs {t['from']!r} but breaker was {state!r}"))
                break
            state = t["to"]
        else:
            if state != ev["final_state"]:
                out.append(Violation(
                    "breaker-opens-within-k",
                    f"dependency {dep}: ledger ends at {state!r} but final "
                    f"state is {ev['final_state']!r}"))
    return out


def check_retry_budget(resilience: "dict | None") -> "list[Violation]":
    """Retry budgets are never exceeded: the token bucket's low-water mark
    stays non-negative (no retry was granted on credit) and refills never
    push it past capacity."""
    out = []
    for dep, ev in sorted((resilience or {}).get("policies", {}).items()):
        b = ev["budget"]
        if b["min_tokens"] < 0:
            out.append(Violation(
                "retry-budget-never-exceeded",
                f"dependency {dep}: budget low-water mark "
                f"{b['min_tokens']:.3f} went negative — a retry was granted "
                f"beyond the budget"))
        if b["tokens"] > b["capacity"] + _COST_EPS:
            out.append(Violation(
                "retry-budget-never-exceeded",
                f"dependency {dep}: budget holds {b['tokens']:.3f} tokens, "
                f"above capacity {b['capacity']:.3f}"))
    return out


def check_degrade_monotone(resilience: "dict | None") -> "list[Violation]":
    """Degradation is monotone during a fault window: every move DOWN the
    ladder (rung index up) is driven by a recorded failure, and every move
    back up is a single-step probe success — no rung skipping, no
    spontaneous recovery, no ledger discontinuities."""
    out = []
    for chain, ev in sorted((resilience or {}).get("ladders", {}).items()):
        rung = 0
        broken = False
        for t in ev["transitions"]:
            if t["from"] != rung:
                out.append(Violation(
                    "degrade-monotone",
                    f"chain {chain}: transition ledger discontinuity — hop "
                    f"departs rung {t['from']} but ladder was at {rung}"))
                broken = True
                break
            if t["to"] > t["from"] and t["reason"] != "failure":
                out.append(Violation(
                    "degrade-monotone",
                    f"chain {chain}: degraded {t['from']} -> {t['to']} "
                    f"with reason {t['reason']!r} (only failures may move "
                    f"the ladder down)"))
            if t["to"] < t["from"]:
                if t["reason"] != "probe-success":
                    out.append(Violation(
                        "degrade-monotone",
                        f"chain {chain}: recovered {t['from']} -> {t['to']} "
                        f"with reason {t['reason']!r} (only probe successes "
                        f"may move the ladder up)"))
                if t["from"] - t["to"] != 1:
                    out.append(Violation(
                        "degrade-monotone",
                        f"chain {chain}: recovery {t['from']} -> {t['to']} "
                        f"skipped rungs (recovery is one probe, one rung)"))
            rung = t["to"]
        if not broken and rung != ev["final_rung"]:
            out.append(Violation(
                "degrade-monotone",
                f"chain {chain}: ledger ends at rung {rung} but final rung "
                f"is {ev['final_rung']}"))
    return out


def check_exactly_once_launch(cloud) -> "list[Violation]":
    """Exactly-once launch across restart: no machine name may ever own
    two live cloud instances. The crash drill's sharpest edge — a fleet
    call that ran, a process that died before recording it, and a reborn
    leader that must adopt-or-reap, never relaunch on top."""
    from ..providers.instance import TAG_MACHINE

    owners: "dict[str, list[str]]" = {}
    with cloud.lock:
        for inst in cloud.instances.values():
            if inst.state == "terminated":
                continue
            machine = inst.tags.get(TAG_MACHINE, "")
            if machine:
                owners.setdefault(machine, []).append(inst.id)
    return [
        Violation("exactly-once-launch",
                  f"machine {name} owns {len(iids)} live instances: "
                  f"{sorted(iids)}")
        for name, iids in sorted(owners.items()) if len(iids) > 1
    ]


def check_journal_resolved(op) -> "list[Violation]":
    """Every write-ahead intent record reaches a terminal state: at
    quiescence the journal is empty — nothing is in flight, so nothing may
    still claim to be."""
    journal = getattr(op, "journal", None)
    if journal is None:
        return []
    return [
        Violation("journal-resolved",
                  f"intent record {rec.name} (epoch {rec.epoch}) still "
                  "pending at quiescence")
        for rec in journal.pending()
    ]


def check_fencing(attempts: int, rejected: int) -> "list[Violation]":
    """Fencing rejects zombie writes: every mutation a deposed ex-leader
    attempted after the epoch advanced must have been refused by the
    store."""
    if attempts == rejected:
        return []
    return [Violation(
        "fencing-rejects-zombie-writes",
        f"{attempts - rejected} of {attempts} zombie write(s) were accepted "
        "after the fencing epoch advanced")]


def check_fairness_never_starves(fleet: "dict | None") -> "list[Violation]":
    """The fleet frontend's fairness contract (fleet/frontend.py): no
    served request ever waited past the starvation bound, every tenant
    that submitted made progress (served or explicitly shed — never
    silently parked), and the drain left nothing queued. Evidence is
    `FleetFrontend.evidence()` captured after the storm drains."""
    out = []
    if not fleet:
        return out
    bound = fleet["starvation_bound"]
    for tid, st in sorted(fleet.get("tenants", {}).items()):
        if st["max_wait_ticks"] > bound:
            out.append(Violation(
                "fairness-never-starves",
                f"tenant {tid}: a served request waited "
                f"{st['max_wait_ticks']} tick(s), past the starvation "
                f"bound {bound}"))
        unresolved = (st["submitted"] - st["served"] - st["shed_admission"]
                      - st["shed_queue"] - st["errors"])
        if st["submitted"] and st["served"] == 0 and unresolved > 0:
            out.append(Violation(
                "fairness-never-starves",
                f"tenant {tid}: submitted {st['submitted']} request(s) and "
                f"was never served nor shed"))
    if fleet.get("queued"):
        out.append(Violation(
            "fairness-never-starves",
            f"{fleet['queued']} request(s) still queued after the drain"))
    return out


def check_shed_attribution(attribution: "dict | None", totals: dict,
                           tenants: dict) -> "list[Violation]":
    """Shed attribution sums match totals: the per-tenant x where x reason
    table (FleetFrontend.shed_attribution()) must account for EVERY shed
    the ledger counted — per tenant (each tenant's attributed sheds equal
    its ledger counters) and in aggregate (the table's admission/queue sums
    equal the storm totals). An attribution that under-counts would let a
    fairness drill blame the wrong tenant; one that over-counts would
    invent shedding that never happened."""
    inv = "shed-attribution-sums-match-totals"
    out = []
    attribution = attribution or {}
    attr_admission = attr_queue = 0
    for tid, entry in sorted(attribution.items()):
        a = sum((entry.get("admission") or {}).values())
        q = sum((entry.get("queue") or {}).values())
        attr_admission += a
        attr_queue += q
        st = tenants.get(tid)
        if st is None:
            out.append(Violation(
                inv, f"attribution names tenant {tid!r} the ledger never "
                     f"saw"))
            continue
        if a != st["shed_admission"] or q != st["shed_queue"]:
            out.append(Violation(
                inv, f"tenant {tid}: attribution says "
                     f"admission={a}/queue={q}, ledger says "
                     f"admission={st['shed_admission']}/"
                     f"queue={st['shed_queue']}"))
    # tenants with sheds but no attribution row
    for tid, st in sorted(tenants.items()):
        if (st["shed_admission"] or st["shed_queue"]) \
                and tid not in attribution:
            out.append(Violation(
                inv, f"tenant {tid} shed "
                     f"{st['shed_admission'] + st['shed_queue']} "
                     f"request(s) but has no attribution row"))
    if attr_admission != totals.get("shed_admission", 0) \
            or attr_queue != totals.get("shed_queue", 0):
        out.append(Violation(
            inv, f"attribution sums admission={attr_admission}/"
                 f"queue={attr_queue} != totals "
                 f"admission={totals.get('shed_admission', 0)}/"
                 f"queue={totals.get('shed_queue', 0)}"))
    return out


def check_columnar_coherence(op) -> "list[Violation]":
    """The columnar mirror IS the cluster: every incrementally-maintained
    column and aggregate equals what a from-scratch rebuild of the node set
    would produce. Catches a missed delta anywhere in the StateNode
    write-interception path — the failure mode an incremental design trades
    for its O(1) updates."""
    import numpy as np

    from ..models.cluster import ANNOTATION_DO_NOT_CONSOLIDATE

    inv = "columnar-coherence"
    out = []
    cluster = op.cluster
    cols = getattr(cluster, "columns", None)
    if cols is None:
        return out

    def bad(msg):
        out.append(Violation(inv, msg))

    # row interning is a bijection over exactly the live node set
    if set(cols.row_of) != set(cluster.nodes):
        bad("row interning desynced from the node set: "
            f"{sorted(set(cols.row_of) ^ set(cluster.nodes))}")
        return out
    if list(cluster._sorted_names) != sorted(cluster.nodes):
        bad("sorted-names cache out of order or out of sync")
    if set(np.nonzero(cols.occupied)[0].tolist()) != set(cols.row_of.values()):
        bad("occupied mask disagrees with the row interning table")
    for name, node in sorted(cluster.nodes.items()):
        row = cols.row_of[name]
        if cols.name_of[row] != name:
            bad(f"name_of[{row}] = {cols.name_of[row]!r}, expected {name!r}")
        fresh = [0] * len(node.allocatable)
        non_daemon = 0
        for p in node.pods:
            for i, v in enumerate(p.resource_vector()):
                fresh[i] += v
            if p.owner_kind != "DaemonSet":
                non_daemon += 1
        if list(cols.used[row]) != fresh or node.used_vector() != fresh:
            bad(f"node {name}: used column/aggregate != pod-scan sum")
        if int(cols.non_daemon[row]) != non_daemon:
            bad(f"node {name}: non_daemon column {int(cols.non_daemon[row])}"
                f" != scan {non_daemon}")
        if list(cols.alloc[row]) != list(node.allocatable):
            bad(f"node {name}: alloc column != node.allocatable")
        if cols.price[row] != node.price:
            bad(f"node {name}: price column out of sync")
        for attr, col in (("marked_for_deletion", cols.marked),
                          ("initialized", cols.initialized),
                          ("drifted", cols.drifted)):
            if bool(col[row]) != bool(getattr(node, attr)):
                bad(f"node {name}: {attr} column out of sync")
        veto = node.annotations.get(ANNOTATION_DO_NOT_CONSOLIDATE) == "true"
        if bool(cols.no_consolidate[row]) != veto:
            bad(f"node {name}: do-not-consolidate column out of sync")
        if cols.prov_names[cols.prov_code[row]] != node.provisioner_name:
            bad(f"node {name}: provisioner code decodes to "
                f"{cols.prov_names[cols.prov_code[row]]!r}")
        if tuple(cols.taint_sets[cols.taint_code[row]]) != tuple(node.taints):
            bad(f"node {name}: taint-set code out of sync")
    # per-provisioner running totals vs the full scan they replaced
    prov_names = ({n.provisioner_name for n in cluster.nodes.values()}
                  | set(cluster._prov_totals))
    for pname in sorted(prov_names):
        from ..apis import wellknown as wk

        cpu = mem = 0
        for n in cluster.nodes.values():
            if n.provisioner_name != pname:
                continue
            cpu += n.allocatable[wk.RESOURCE_INDEX[wk.RESOURCE_CPU]]
            mem += n.allocatable[wk.RESOURCE_INDEX[wk.RESOURCE_MEMORY]] * 2**20
        if cluster.total_usage(pname) != (cpu, mem):
            bad(f"provisioner {pname}: running totals "
                f"{cluster.total_usage(pname)} != scan {(cpu, mem)}")
    # incremental PDB healthy counts vs a full pod recount
    recount = {
        pdb.name: sum(1 for n in cluster.nodes.values()
                      for p in n.pods if pdb.matches(p))
        for pdb in cluster.pdbs
    }
    if cluster.pdb_healthy() != recount:
        bad(f"pdb healthy counts {cluster.pdb_healthy()} != recount {recount}")
    return out


def check_profiling_noop(profiling) -> "list[Violation]":
    """profiling-strict-noop: the profiling plane is advisory — with the
    plane disabled it must do NOTHING. The runner disables profiling for
    the scenario and hands us before/after activity counters
    (karpenter_tpu.profiling.activity()); ANY growth — host samples,
    device events, gap-ledger rows, ring lengths — means a producer
    ignored the switch and the plane has become load-bearing."""
    if not profiling or profiling.get("enabled", True):
        return []  # not part of this drill, or plane was left on
    out: "list[Violation]" = []
    before = profiling.get("before") or {}
    after = profiling.get("after") or {}
    for key in sorted(set(before) | set(after)):
        grew = after.get(key, 0) - before.get(key, 0)
        if grew > 0:
            out.append(Violation(
                "profiling-strict-noop",
                f"profiling disabled but {key} grew by {grew} "
                f"({before.get(key, 0)} -> {after.get(key, 0)})"))
    return out


def check_critical_noop(critical) -> "list[Violation]":
    """critical-strict-noop: the critical-path ledger is advisory — with
    KARPENTER_TPU_CRITICAL off the gap ledger's flat accumulation keeps
    working but NO interval records, wait notes, or ring rows may appear.
    The runner runs a probe window with the plane disabled and hands us
    before/after activity counters (karpenter_tpu.profiling.critical
    .activity()); ANY growth means a producer ignored the switch and the
    chain view has become load-bearing."""
    if not critical or critical.get("enabled", True):
        return []  # not part of this drill, or plane was left on
    out: "list[Violation]" = []
    before = critical.get("before") or {}
    after = critical.get("after") or {}
    for key in sorted(set(before) | set(after)):
        grew = after.get(key, 0) - before.get(key, 0)
        if grew > 0:
            out.append(Violation(
                "critical-strict-noop",
                f"critical ledger disabled but {key} grew by {grew} "
                f"({before.get(key, 0)} -> {after.get(key, 0)})"))
    return out


def check_explain_noop(explain) -> "list[Violation]":
    """explain-strict-noop: the decision-provenance plane is advisory —
    with the plane disabled it must do NOTHING. The runner disables
    explain for the scenario and hands us before/after activity counters
    (karpenter_tpu.explain.activity()); ANY growth — records emitted,
    attributions run, sheds or consolidations noted, ring depth — means
    a producer ignored the switch and the plane has become
    load-bearing."""
    if not explain or explain.get("enabled", True):
        return []  # not part of this drill, or plane was left on
    out: "list[Violation]" = []
    before = explain.get("before") or {}
    after = explain.get("after") or {}
    for key in sorted(set(before) | set(after)):
        grew = after.get(key, 0) - before.get(key, 0)
        if grew > 0:
            out.append(Violation(
                "explain-strict-noop",
                f"explain disabled but {key} grew by {grew} "
                f"({before.get(key, 0)} -> {after.get(key, 0)})"))
    return out


def check_membership_noop(membership) -> "list[Violation]":
    """membership-strict-noop: the membership plane is advisory — with
    the plane disabled it must do NOTHING. The runner disables membership
    for the scenario and hands us before/after activity counters
    (karpenter_tpu.fleet.membership.activity()); ANY growth — probes
    issued, transitions fired, epoch bumps — means the plane mutated
    routing behind the switch and static membership is no longer
    bit-identical."""
    if not membership or membership.get("enabled", True):
        return []  # not part of this drill, or plane was left on
    out: "list[Violation]" = []
    before = membership.get("before") or {}
    after = membership.get("after") or {}
    for key in sorted(set(before) | set(after)):
        grew = after.get(key, 0) - before.get(key, 0)
        if grew > 0:
            out.append(Violation(
                "membership-strict-noop",
                f"membership disabled but {key} grew by {grew} "
                f"({before.get(key, 0)} -> {after.get(key, 0)})"))
    return out


def check_incremental_noop(incremental) -> "list[Violation]":
    """incremental-strict-noop: the delta-aware solving plane is an
    optimization, never load-bearing — with KARPENTER_TPU_INCREMENTAL off
    every solve is the legacy full solve and the plane does NOTHING. The
    runner disables it for the scenario and hands us before/after
    activity counters (karpenter_tpu.incremental.activity()); ANY growth
    — cycles entered, subproblems extracted, masks patched, escapes
    tripped — means a producer ignored the switch."""
    if not incremental or incremental.get("enabled", True):
        return []  # not part of this drill, or plane was left on
    out: "list[Violation]" = []
    before = incremental.get("before") or {}
    after = incremental.get("after") or {}
    for key in sorted(set(before) | set(after)):
        grew = after.get(key, 0) - before.get(key, 0)
        if grew > 0:
            out.append(Violation(
                "incremental-strict-noop",
                f"incremental disabled but {key} grew by {grew} "
                f"({before.get(key, 0)} -> {after.get(key, 0)})"))
    return out


def check_spot_noop(spot) -> "list[Violation]":
    """spot-strict-noop: the spot-storm resilience plane is advisory —
    with KARPENTER_TPU_SPOT=0 the forecaster serves 0.0/1.0 constants,
    the risk objective never activates, and the rebalance controller
    returns before touching anything. The runner runs a disabled probe
    window (forecast refresh + rate lookups + rebalance reconciles) and
    hands us before/after activity counters (karpenter_tpu.spot
    .activity()); ANY growth means a producer ignored the switch and the
    advisory plane has become load-bearing."""
    if not spot or spot.get("enabled", True):
        return []  # not part of this drill, or plane was left on
    out: "list[Violation]" = []
    before = spot.get("before") or {}
    after = spot.get("after") or {}
    for key in sorted(set(before) | set(after)):
        grew = after.get(key, 0) - before.get(key, 0)
        if grew > 0:
            out.append(Violation(
                "spot-strict-noop",
                f"spot plane disabled but {key} grew by {grew} "
                f"({before.get(key, 0)} -> {after.get(key, 0)})"))
    return out


def check_overload_noop(overload) -> "list[Violation]":
    """overload-strict-noop: the overload/backpressure plane is graduated
    and OPTIONAL — with KARPENTER_TPU_OVERLOAD=0 every guard observation
    returns accept, the admission filter admits everything straight to
    the main LRU, low-water eviction never runs, and the simulated-RSS
    hook counts nothing. The runner drives a disabled probe window (guard
    observations under synthetic pressure + admission offers + decide
    calls) and hands us before/after activity counters
    (karpenter_tpu.overload.activity()); ANY growth means a producer
    ignored the switch and backpressure leaked into the disabled path."""
    if not overload or overload.get("enabled", True):
        return []  # not part of this drill, or plane was left on
    out: "list[Violation]" = []
    before = overload.get("before") or {}
    after = overload.get("after") or {}
    for key in sorted(set(before) | set(after)):
        grew = after.get(key, 0) - before.get(key, 0)
        if grew > 0:
            out.append(Violation(
                "overload-strict-noop",
                f"overload plane disabled but {key} grew by {grew} "
                f"({before.get(key, 0)} -> {after.get(key, 0)})"))
    decisions = overload.get("decisions") or []
    wrong = [d for d in decisions if d != "accept"]
    if wrong:
        out.append(Violation(
            "overload-strict-noop",
            f"overload plane disabled but {len(wrong)} probe decision(s) "
            f"were not 'accept': {sorted(set(wrong))}"))
    return out


def check_spot_cost_never_raised(ledger: "list[dict]") -> "list[Violation]":
    """spot-cost-never-raised: every proactive rebalance replacement must
    cost (sticker price) no more than the at-risk node it relieves —
    _safe_offering guarantees it by construction, this audits the
    receipts the controller banked for each launched replacement."""
    out: "list[Violation]" = []
    for entry in ledger:
        if entry["replacement_price"] > entry["node_price"] + 1e-9:
            out.append(Violation(
                "spot-cost-never-raised",
                f"rebalance replaced {entry['node']} "
                f"(${entry['node_price']}/h) with {entry['replacement']} "
                f"(${entry['replacement_price']}/h) — proactive churn "
                f"raised the bill"))
    return out


def check_spot_capacity_restored(restore_cycles: int,
                                 k: int) -> "list[Violation]":
    """spot-capacity-restored-within-k: after the reclaim storm every
    displaced pod must be bound again within K reconcile cycles."""
    if restore_cycles < 0:
        return [Violation(
            "spot-capacity-restored-within-k",
            f"capacity was never fully restored within the drill window "
            f"(bound: {k} cycles)")]
    if restore_cycles > k:
        return [Violation(
            "spot-capacity-restored-within-k",
            f"capacity took {restore_cycles} cycles to restore "
            f"(bound: {k})")]
    return []


def check_spot_never_strands(op, ledger: "list[dict]") -> "list[Violation]":
    """spot-rebalance-never-strands: a proactive drain may only have
    fired against a node whose replacement reached initialized (two-phase
    order), and at drill end no workload pod is left unbound while its
    node was proactively drained. Evidence: the rebalance ledger plus the
    final pending-pod set."""
    out: "list[Violation]" = []
    pending = op.kube.pending_pods()
    if pending:
        drained = sorted(e["node"] for e in ledger)
        out.append(Violation(
            "spot-rebalance-never-strands",
            f"{len(pending)} pod(s) still pending after settle "
            f"({sorted(p.name for p in pending)[:5]}...) with "
            f"{len(drained)} proactive drain(s) in the ledger"))
    return out


def check_incremental_parity(incremental) -> "list[Violation]":
    """incremental-parity-never-diverges: whenever the plane IS on, every
    incremental solve carries a scalar-oracle bit-parity audit on the
    dirty subproblem; a divergence means the small solve would have bound
    pods differently from the full solve. The plane falls back to the
    full solve when it happens (correctness survives), but the event
    itself is the invariant violation — the extractor's soundness
    argument failed. Evidence: before/after activity counters from an
    ENABLED window; the audit_divergences counter must not move."""
    if not incremental:
        return []
    if not incremental.get("enabled", True):
        return []  # the noop check covers the disabled window
    before = (incremental.get("before") or {}).get("audit_divergences", 0)
    after = (incremental.get("after") or {}).get("audit_divergences", 0)
    if after > before:
        return [Violation(
            "incremental-parity-never-diverges",
            f"bit-parity audit diverged {after - before} time(s) during "
            f"the scenario ({before} -> {after}): the dirty-subproblem "
            f"solve disagreed with the scalar oracle")]
    return []


def check_remap_blast_radius(before: "dict[str, str]",
                             after: "dict[str, str]",
                             lost: "set[str] | list[str]",
                             ) -> "list[Violation]":
    """remap-blast-radius: when replicas leave the member set, EXACTLY
    the tenants homed on them remap — a tenant whose home survived must
    keep it (rendezvous stability is the whole point), and no tenant may
    keep routing to a lost replica. `before`/`after` are full
    tenant->replica assignments bracketing the loss; `lost` is the set
    of replicas that left."""
    inv = "remap-blast-radius"
    lost_set = set(lost)
    out = []
    for tenant in sorted(before):
        home, now = before[tenant], after.get(tenant)
        if now is None:
            out.append(Violation(
                inv, f"tenant {tenant} vanished from the assignment after "
                     f"losing {sorted(lost_set)}"))
        elif home in lost_set and now == home:
            out.append(Violation(
                inv, f"tenant {tenant} still routes to lost replica "
                     f"{home}"))
        elif home not in lost_set and now != home:
            out.append(Violation(
                inv, f"tenant {tenant} remapped {home} -> {now} but its "
                     f"home never left the member set (blast radius "
                     f"exceeded)"))
    return out


def check_completes_or_sheds(outcomes: "list[dict]") -> "list[Violation]":
    """solve-completes-or-sheds: every admitted solve reaches a terminal
    outcome — served, or shed with a vocabulary reason. A request that
    silently vanished (no outcome), errored out of the failover path, or
    shed citing a reason outside explain/reasons.py SHED_REASONS is a
    violation: under replica churn "we lost it somewhere" is exactly the
    failure mode this plane exists to kill."""
    from ..explain.reasons import SHED_REASONS

    inv = "solve-completes-or-sheds"
    out = []
    for i, rec in enumerate(outcomes):
        tenant = rec.get("tenant", f"#{i}")
        outcome = rec.get("outcome")
        if outcome == "served":
            continue
        if outcome == "shed":
            reason = rec.get("reason")
            if reason not in SHED_REASONS:
                out.append(Violation(
                    inv, f"tenant {tenant}: shed with reason {reason!r} "
                         f"not in the SHED_REASONS vocabulary"))
            continue
        out.append(Violation(
            inv, f"tenant {tenant}: solve ended as {outcome!r} "
                 f"(expected served or shed-with-reason)"))
    return out


def check_quarantine_cascade(victims: "dict[str, list]",
                             limit: int = 2) -> "list[Violation]":
    """quarantine-bounds-cascade: no request fingerprint may fell more
    than `limit` distinct replicas — the quarantine ring must trip on the
    second victim and shed every later attempt, never hand the poison a
    third target. `victims` is the ring's fingerprint -> victim-replicas
    evidence."""
    return [
        Violation("quarantine-bounds-cascade",
                  f"request {fp} took down {len(reps)} replicas "
                  f"{sorted(reps)} (quarantine must cap the cascade at "
                  f"{limit})")
        for fp, reps in sorted(victims.items()) if len(set(reps)) > limit
    ]


def check_epoch_monotone(epochs: "list[int]") -> "list[Violation]":
    """membership-epoch-monotone: the observed membership epoch sequence
    never regresses. Epochs are how observers (fleetz, clients) order
    membership views; one regression and a stale view can masquerade as
    the freshest."""
    out = []
    prev = None
    for i, epoch in enumerate(epochs):
        if prev is not None and epoch < prev:
            out.append(Violation(
                "membership-epoch-monotone",
                f"epoch regressed at observation #{i}: {prev} -> {epoch}"))
        prev = epoch
    return out


def check_scrape_evidence(rows: "dict[str, dict]",
                          expect_pids: "dict[str, int] | None" = None,
                          ) -> "list[Violation]":
    """scrape-evidence-complete: the real-replica drill audits its
    invariants from FEDERATED SCRAPE EVIDENCE, so the evidence itself is
    audited first. Every healthy /debug/fleetz row must carry the scrape
    provenance fields (scrape_ms, pid — proof the row came over a live
    HTTP round-trip from a real process, not a stub), the pid must match
    the rendezvous record when one is expected, and an unhealthy row
    must NAME its failure (error text; transport failures additionally
    carry the classified scrape_error kind)."""
    inv = "scrape-evidence-complete"
    out = []
    for name, row in sorted(rows.items()):
        if not isinstance(row, dict):
            out.append(Violation(inv, f"replica {name}: row is not a dict"))
            continue
        if row.get("healthy"):
            for field in ("scrape_ms", "pid"):
                if not isinstance(row.get(field), (int, float)):
                    out.append(Violation(
                        inv, f"replica {name}: healthy row missing scrape "
                             f"provenance field {field!r}"))
            expected = (expect_pids or {}).get(name)
            if expected is not None and row.get("pid") != expected:
                out.append(Violation(
                    inv, f"replica {name}: scraped pid {row.get('pid')} != "
                         f"registered pid {expected} (the row did not come "
                         f"from the process it claims)"))
        elif not row.get("error"):
            out.append(Violation(
                inv, f"replica {name}: unhealthy row with no named error "
                     f"(partial-scrape degradation must name the corpse)"))
    return out


def check_kill_absorbed(cycles: "list[dict]", victim: str,
                        limit: int = 3) -> "list[Violation]":
    """kill-absorbed-within-cycles: after a replica is killed mid-run,
    the membership plane must absorb the loss within `limit` recovery
    cycles — the victim ejected from the member set AND every survivor
    still a member. `cycles` is the drill's post-kill probe-cycle log,
    one dict per cycle: {"members": [...], "ejected": [...]}."""
    inv = "kill-absorbed-within-cycles"
    for i, cyc in enumerate(cycles):
        if victim in (cyc.get("ejected") or ()):  # absorbed at cycle i+1
            if i + 1 > limit:
                return [Violation(
                    inv, f"victim {victim} ejected only at post-kill "
                         f"cycle {i + 1} (limit {limit})")]
            return []
    return [Violation(
        inv, f"victim {victim} never ejected across {len(cycles)} "
             f"post-kill cycles (limit {limit})")]


def check_survivors_progress(before: "dict[str, int]",
                             after: "dict[str, int]",
                             lost: "set[str] | list[str]",
                             ) -> "list[Violation]":
    """survivors-make-progress: SLO recovery, read purely from scraped
    per-replica served totals (frontend stats "served"). Bracketing the
    kill, every SURVIVING replica's served count must strictly increase
    — traffic remapped off the corpse and kept completing — and no
    counter may regress (a regression means the scrape mixed up replica
    identities or a replica silently restarted)."""
    inv = "survivors-make-progress"
    lost_set = set(lost)
    out = []
    for name in sorted(before):
        b, a = before[name], after.get(name)
        if name in lost_set:
            continue
        if a is None:
            out.append(Violation(
                inv, f"surviving replica {name} has no post-kill served "
                     f"count (scrape lost it)"))
        elif a < b:
            out.append(Violation(
                inv, f"replica {name} served count regressed {b} -> {a}"))
        elif a == b:
            out.append(Violation(
                inv, f"surviving replica {name} made no progress after "
                     f"the kill (served stuck at {b})"))
    return out


def check_all(op, cloud, token_launches=None,
              consolidation_actions=None,
              resilience=None, profiling=None,
              explain=None, membership=None,
              incremental=None, critical=None,
              spot=None, overload=None) -> "list[Violation]":
    out = []
    out += check_token_ledger(token_launches or {})
    out += check_bijection(op, cloud)
    out += check_binds(op)
    out += check_termination_terminal(op, cloud)
    out += check_consolidation_cost(consolidation_actions or [])
    out += check_breaker_discipline(resilience)
    out += check_retry_budget(resilience)
    out += check_degrade_monotone(resilience)
    out += check_columnar_coherence(op)
    out += check_profiling_noop(profiling)
    # the critical plane runs a dedicated probe window after the scenario
    # (enabled evidence + disabled strict-noop) — see chaos/runner.py
    out += check_critical_noop((critical or {}).get("noop"))
    out += check_explain_noop(explain)
    out += check_membership_noop(membership)
    # the incremental plane carries TWO windows: the chaotic cycles run
    # with the plane ON (parity evidence) and the settle runs with it OFF
    # (strict-noop evidence) — see chaos/runner.py run_scenario
    inc = incremental or {}
    out += check_incremental_noop(inc.get("noop"))
    out += check_incremental_parity(inc.get("parity"))
    # the spot plane runs a dedicated disabled probe window after the
    # scenario (two-window evidence, same shape as the critical plane) —
    # see chaos/runner.py
    out += check_spot_noop((spot or {}).get("noop"))
    # the overload plane runs the same two-window probe shape: window A
    # disabled under synthetic pressure (counters must freeze, decisions
    # must all be accept), window B enabled (counters must move) — see
    # chaos/runner.py
    out += check_overload_noop((overload or {}).get("noop"))
    return out
