"""Seeded fault plans: one integer seed -> a deterministic fault schedule.

The plan is the chaos plane's source of truth (docs/designs/chaos.md).
It owns its PRNG (splitmix64 — the same generator family JAX uses for
threefry key splitting; zero dependencies, no `random`-module state, no
wall clock), so two processes given the same seed derive byte-identical
schedules. Faults are scheduled at named SITES by call index: "the 3rd
CreateFleet raises a 5xx", "cycle 7 injects a spot-interruption burst".
Whether a scheduled fault actually FIRES depends on how many times the
run reaches that site — the fired sequence is the replay artifact
(runner.py), the plan is the contract.
"""

from __future__ import annotations

import dataclasses

# -- fault kinds (>=6 kinds across >=3 layers; ISSUE 2 tentpole) -------------

# cloud backend layer
KIND_CLOUD_5XX = "cloud-5xx"              # CreateFleet/Describe/Terminate InternalError
KIND_CLOUD_TIMEOUT = "cloud-timeout"      # API call hangs past the client deadline
KIND_CLOUD_ICE = "cloud-ice"              # pool goes InsufficientInstanceCapacity
KIND_WIRE_5XX_POST_DISPATCH = "wire-5xx-post-dispatch"  # 500 AFTER the launch ran
# kube coordination layer
KIND_KUBE_REQ_DISCONNECT = "kube-req-disconnect"    # write lost before the apply
KIND_KUBE_RESP_DISCONNECT = "kube-resp-disconnect"  # write APPLIED, response lost
KIND_KUBE_WATCH_RESET = "kube-watch-reset"          # watch drop -> relist echo storm
# solver layer
KIND_SOLVER_CRASH = "solver-crash"        # sidecar dies mid-Solve (SolverUnavailable)
# environment layer
KIND_SPOT_BURST = "spot-burst"            # interruption warnings for running spot
KIND_CLOCK_SKEW = "clock-skew"            # fake clock jumps forward
# process layer
KIND_CRASH = "crash"                      # process dies at a named crashpoint
# overload layer (ISSUE 20): resource-pressure faults the overload plane
# must absorb — and must NOT react to while disabled (strict noop)
KIND_HOST_MEM_PRESSURE = "host-memory-pressure"  # simulated RSS pins at the cap
KIND_WATCH_FLOOD = "watch-event-flood"           # repeated watch resets, one cycle
KIND_KUBE_429 = "kube-429-throttle"              # write throttled w/ Retry-After

LAYER_OF_KIND = {
    KIND_CLOUD_5XX: "cloud",
    KIND_CLOUD_TIMEOUT: "cloud",
    KIND_CLOUD_ICE: "cloud",
    KIND_WIRE_5XX_POST_DISPATCH: "cloud",
    KIND_KUBE_REQ_DISCONNECT: "kube",
    KIND_KUBE_RESP_DISCONNECT: "kube",
    KIND_KUBE_WATCH_RESET: "kube",
    KIND_SOLVER_CRASH: "solver",
    KIND_SPOT_BURST: "environment",
    KIND_CLOCK_SKEW: "environment",
    KIND_CRASH: "process",
    KIND_HOST_MEM_PRESSURE: "environment",
    KIND_WATCH_FLOOD: "kube",
    KIND_KUBE_429: "kube",
}

# -- sites -------------------------------------------------------------------
# Call-indexed sites are consulted once per call through the hook; cycle
# sites once per runner cycle. (site -> candidate kinds)

CALL_SITES = {
    "cloud.create_fleet": (KIND_CLOUD_5XX, KIND_CLOUD_TIMEOUT),
    "cloud.describe": (KIND_CLOUD_5XX, KIND_CLOUD_TIMEOUT),
    "cloud.terminate": (KIND_CLOUD_5XX,),
    "kube.write": (KIND_KUBE_REQ_DISCONNECT, KIND_KUBE_RESP_DISCONNECT,
                   KIND_KUBE_429),
    "solver.solve": (KIND_SOLVER_CRASH,),
    # armed only when the scenario runs over the wire (runner wire=True)
    "wire.create_fleet": (KIND_WIRE_5XX_POST_DISPATCH,),
}

CYCLE_SITES = {
    "cycle.ice": (KIND_CLOUD_ICE,),
    "cycle.spot": (KIND_SPOT_BURST,),
    "cycle.clock": (KIND_CLOCK_SKEW,),
    "cycle.watch": (KIND_KUBE_WATCH_RESET,),
    "cycle.mem": (KIND_HOST_MEM_PRESSURE,),
    "cycle.watchflood": (KIND_WATCH_FLOOD,),
}


def crash_sites() -> "dict[str, tuple]":
    """Call-indexed sites for the crash drill: one per named crashpoint
    (recovery/crashpoints.py CRASHPOINTS), armed only by FaultPlan.crash —
    from_seed never schedules process death, so the standard sweeps keep
    their in-process convergence semantics."""
    from ..recovery.crashpoints import CRASHPOINTS

    return {f"crash.{site}": (KIND_CRASH,) for site in CRASHPOINTS}


SITES = tuple(sorted(list(CALL_SITES) + list(CYCLE_SITES)))

_MASK = (1 << 64) - 1


class ChaosRng:
    """splitmix64: tiny, fast, full-period, and trivially forkable —
    every derived stream is a pure function of (seed, label)."""

    def __init__(self, seed: int):
        self._state = seed & _MASK

    def next_u64(self) -> int:
        self._state = (self._state + 0x9E3779B97F4A7C15) & _MASK
        z = self._state
        z = ((z ^ (z >> 30)) * 0xBF58476D1CE4E5B9) & _MASK
        z = ((z ^ (z >> 27)) * 0x94D049BB133111EB) & _MASK
        return z ^ (z >> 31)

    def uniform(self) -> float:
        return self.next_u64() / float(1 << 64)

    def randint(self, lo: int, hi: int) -> int:
        """Uniform integer in [lo, hi] inclusive."""
        if hi <= lo:
            return lo
        return lo + self.next_u64() % (hi - lo + 1)

    def choice(self, seq):
        return seq[self.next_u64() % len(seq)]

    def sample_indices(self, k: int, horizon: int) -> "list[int]":
        """k distinct indices in [0, horizon), sorted."""
        k = min(k, horizon)
        picked: "set[int]" = set()
        while len(picked) < k:
            picked.add(self.next_u64() % horizon)
        return sorted(picked)

    def fork(self, label: str) -> "ChaosRng":
        """Derived stream: mixing the label through the generator itself
        keeps forks independent without hashing machinery."""
        h = ChaosRng(self._state ^ 0xA5A5A5A5A5A5A5A5)
        for ch in label:
            h._state = (h._state ^ ord(ch)) & _MASK
            h.next_u64()
        return ChaosRng(h.next_u64())


@dataclasses.dataclass(frozen=True)
class FaultSpec:
    site: str
    index: int   # 0-based call (or cycle) index at which the fault fires
    kind: str
    param: float = 0.0  # kind-specific magnitude (skew seconds, burst size, ...)

    def as_dict(self) -> dict:
        return {"site": self.site, "index": self.index,
                "kind": self.kind, "param": self.param}


class FaultPlan:
    """The full schedule for one scenario. `at(site, index)` is the only
    hot-path query; disabled sites are absent from the map entirely."""

    # how deep into a site's call stream faults may land; kept small so a
    # short scenario actually reaches the scheduled indices
    CALL_HORIZON = 12
    CYCLE_HORIZON = 14  # must stay < ChaosRunner.CHAOS_CYCLES

    def __init__(self, seed: int, scenario: int = 0,
                 faults: "dict[str, dict[int, FaultSpec]]" = None):
        self.seed = seed
        self.scenario = scenario
        self.faults = faults or {}

    @classmethod
    def from_seed(cls, seed: int, scenario: int = 0, wire: bool = False,
                  intensity: float = 1.0) -> "FaultPlan":
        """Derive the schedule. `intensity` scales fault counts (the slow
        sweep turns it up); wire=False leaves wire.* sites unarmed."""
        root = ChaosRng((seed << 8) ^ scenario)
        faults: "dict[str, dict[int, FaultSpec]]" = {}
        for site in sorted(CALL_SITES):
            if site.startswith("wire.") and not wire:
                continue
            kinds = CALL_SITES[site]
            r = root.fork(site)
            count = min(r.randint(1, max(1, int(3 * intensity))),
                        cls.CALL_HORIZON)
            per = {}
            for idx in r.sample_indices(count, cls.CALL_HORIZON):
                per[idx] = FaultSpec(site, idx, r.choice(kinds))
            faults[site] = per
        for site in sorted(CYCLE_SITES):
            kinds = CYCLE_SITES[site]
            r = root.fork(site)
            count = min(r.randint(1, max(1, int(2 * intensity))),
                        cls.CYCLE_HORIZON)
            per = {}
            for idx in r.sample_indices(count, cls.CYCLE_HORIZON):
                kind = r.choice(kinds)
                if kind == KIND_CLOCK_SKEW:
                    param = float(r.randint(30, 240))  # seconds jumped
                elif kind == KIND_SPOT_BURST:
                    param = float(r.randint(1, 3))     # instances interrupted
                elif kind == KIND_CLOUD_ICE:
                    param = float(r.randint(2, 5))     # cycles the pool is ICE
                elif kind == KIND_HOST_MEM_PRESSURE:
                    param = float(r.randint(2, 4))     # cycles RSS stays pinned
                elif kind == KIND_WATCH_FLOOD:
                    param = float(r.randint(2, 5))     # resets injected at once
                else:
                    param = 0.0
                per[idx] = FaultSpec(site, idx, kind, param)
            faults[site] = per
        return cls(seed, scenario, faults)

    @classmethod
    def crash(cls, seed: int, site: str, scenario: int = 0,
              index: int = 0) -> "FaultPlan":
        """The crash-drill schedule: the process dies exactly once, at the
        named crashpoint's `index`-th reach. Fixed by construction — the
        drill's job is proving each in-flight-intent site recovers, so the
        kill site is the scenario's identity and the seed only varies the
        derived workload."""
        full = f"crash.{site}"
        return cls(seed, scenario,
                   {full: {index: FaultSpec(full, index, KIND_CRASH)}})

    @classmethod
    def burst(cls, seed: int, scenario: int = 0) -> "FaultPlan":
        """The resilience-plane acceptance scenario: a dense cloud-5xx
        burst (every cloud site fails its first 8 calls — enough
        consecutive failures to trip the cloud breaker and drain real
        retry-budget tokens) plus a solver-crash window (first 6 solves —
        enough to walk the solve ladder down). The schedule is fixed by
        construction; the seed only varies the derived workload."""
        faults: "dict[str, dict[int, FaultSpec]]" = {}
        for site in ("cloud.create_fleet", "cloud.describe",
                     "cloud.terminate"):
            faults[site] = {i: FaultSpec(site, i, KIND_CLOUD_5XX)
                            for i in range(8)}
        faults["solver.solve"] = {
            i: FaultSpec("solver.solve", i, KIND_SOLVER_CRASH)
            for i in range(6)}
        return cls(seed, scenario, faults)

    def at(self, site: str, index: int) -> "FaultSpec | None":
        per = self.faults.get(site)
        if per is None:
            return None
        return per.get(index)

    def describe(self) -> "list[dict]":
        out = []
        for site in sorted(self.faults):
            for idx in sorted(self.faults[site]):
                out.append(self.faults[site][idx].as_dict())
        return out

    def scheduled_kinds(self) -> "set[str]":
        return {f.kind for per in self.faults.values() for f in per.values()}
