"""Site hooks: wire a FaultPlan into a live operator.

Each hook wraps one boundary the production stack already crosses —
the cloud API (below the batchers, so coalescing/retry behavior is
exercised), the kube write surface, the solver client, and the wire
cloud-API server — and consults the plan by per-site call index. When
the injector is disabled the hooks are a strict no-op fast path: one
attribute read, no locks, no counting.

Determinism: the injector also serializes the operator's worker pools
(launch + interruption, 1 worker each) so every site's call order — and
therefore which call each scheduled index lands on — is a pure function
of the seed. Faults FIRE in deterministic order; the recorded `fired`
sequence is the replay artifact.
"""

from __future__ import annotations

import contextlib
import json
import threading
from concurrent.futures import ThreadPoolExecutor

from ..apis import wellknown as wk
from ..batcher.fleet import (CreateFleetBatcher, DescribeInstancesBatcher,
                             TerminateInstancesBatcher)
from ..coordination.httpkube import ApiError
from ..oracle.scheduler import Scheduler
from ..solver.client import SolverUnavailable
from ..utils import errors as cloud_errors
from . import plan as planmod
from .plan import (KIND_CLOUD_5XX, KIND_CLOUD_ICE, KIND_CLOUD_TIMEOUT,
                   KIND_CLOCK_SKEW, KIND_HOST_MEM_PRESSURE, KIND_KUBE_429,
                   KIND_KUBE_REQ_DISCONNECT, KIND_KUBE_RESP_DISCONNECT,
                   KIND_KUBE_WATCH_RESET, KIND_SOLVER_CRASH,
                   KIND_SPOT_BURST, KIND_WATCH_FLOOD, FaultPlan)


# what a chaos 429 tells the client to wait (seconds; virtual under the
# chaos FakeClock — use_virtual_sleep steps the clock instead of blocking)
KUBE_429_RETRY_AFTER_S = 0.05

# what host-memory-pressure pins the simulated RSS at: far above any
# plausible KARPENTER_TPU_RSS_SOFT_CAP_BYTES, so an armed overload guard
# reads pressure 1.0 while guards without a cap (every legacy scenario)
# read the same number and stay quiet
MEM_PRESSURE_RSS_BYTES = 32 << 30


def shrink_batcher_windows(op) -> None:
    """The default CreateFleet/Describe/Terminate windows (35-100ms
    real-time idle) would dominate a many-cycle scenario's wall clock —
    and make a 10k-node fleet drill take hours of pure batcher latency.
    Sub-ms windows keep the same coalescing code path on the serialized
    call stream."""
    inst = op.cloudprovider.instances
    for attr, cls in (("fleet", CreateFleetBatcher),
                      ("describe", DescribeInstancesBatcher),
                      ("terminate", TerminateInstancesBatcher)):
        old = getattr(inst, attr)
        old.stop()
        # keep the cloud-edge RetryPolicy (breaker + budget) the
        # operator wired in — chaos exists to exercise it
        setattr(inst, attr, cls(inst.cloud, idle=0.0005, max_wait=0.002,
                                policy=getattr(old, "policy", None)))


class _ChaosSolver:
    """Primary-backend stand-in: crashes mid-Solve when the plan says so,
    otherwise delegates to the scalar oracle (pure python — the chaos
    tier needs deterministic, compile-free solves; backend parity is
    proven elsewhere). A crash exercises provisioning's real degrade
    chain: tpu -> native -> oracle."""

    def __init__(self, catalog, provisioners, injector: "ChaosInjector"):
        self._catalog = catalog
        self._provisioners = provisioners
        self._injector = injector

    def solve(self, pods, existing=None, daemon_overhead=None,
              option_mask=None):
        fault = self._injector.maybe("solver.solve")
        if fault is not None:
            raise SolverUnavailable(
                "chaos: solver sidecar crashed mid-Solve")
        from ..controllers.provisioning import _oracle_to_solve_result

        barred = None
        if option_mask is not None:
            # the spot objective's dense mask bars whole (type, zone,
            # capacityType) pools — recover them so the oracle sees the
            # same dimension (axis layout mirrors spot.objective.pool_mask)
            zones = sorted({o.zone for t in self._catalog.types
                            for o in t.offerings})
            cts = list(wk.CAPACITY_TYPES)
            barred = set()
            for ti, t in enumerate(self._catalog.types):
                for zi, z in enumerate(zones):
                    for ci, c in enumerate(cts):
                        if not option_mask[ti, zi * len(cts) + ci]:
                            barred.add((t.name, z, c))
        sched = Scheduler(self._catalog, self._provisioners,
                          daemon_overhead or [0] * wk.NUM_RESOURCES,
                          barred=barred)
        return _oracle_to_solve_result(
            sched.schedule(list(pods), existing=existing or []), sched)


class ChaosInjector:
    def __init__(self, plan: FaultPlan, enabled: bool = True):
        self.plan = plan
        self.enabled = enabled
        self._lock = threading.Lock()
        self._counts: "dict[str, int]" = {}
        self.fired: "list[dict]" = []  # occurrence-ordered (site, index, kind)
        # wire-mode CreateFleet ledger: client token -> inner launches
        self.token_launches: "dict[str, int]" = {}
        self.consolidation_actions: "list[dict]" = []
        # ICE pools currently injected -> cycle index at which they expire
        self._ice_expiry: "dict[tuple[str, str, str], int]" = {}
        # host-memory-pressure fault: cycle index at which the simulated
        # RSS clears again (None = not armed)
        self._mem_expiry: "int | None" = None
        self._cycle_rng = planmod.ChaosRng(
            (plan.seed << 8) ^ plan.scenario).fork("cycle-choices")

    # -- core site query -------------------------------------------------------

    def maybe(self, site: str):
        """Consult the plan at this site's next call index. Returns the
        FaultSpec to apply, or None. Disabled => strict no-op."""
        if not self.enabled:
            return None
        with self._lock:
            idx = self._counts.get(site, 0)
            self._counts[site] = idx + 1
            fault = self.plan.at(site, idx)
            if fault is not None:
                self.fired.append(fault.as_dict())
            return fault

    def site_counts(self) -> "dict[str, int]":
        with self._lock:
            return dict(self._counts)

    def fired_kinds(self) -> "set[str]":
        with self._lock:
            return {f["kind"] for f in self.fired}

    @contextlib.contextmanager
    def paused(self):
        """Harness-internal traffic (workload writes, assertions) must not
        consume fault indices."""
        prev = self.enabled
        self.enabled = False
        try:
            yield
        finally:
            self.enabled = prev

    # -- installation ----------------------------------------------------------

    def install(self, op, cloud) -> None:
        """Hook every hermetic site on an assembled (not started) operator."""
        self._wrap_cloud_api(cloud.create_fleet_api, "cloud.create_fleet")
        self._wrap_cloud_api(cloud.describe_instances_api, "cloud.describe")
        self._wrap_cloud_api(cloud.terminate_instances_api, "cloud.terminate")
        hub = getattr(op, "resilience", None)
        self._wrap_kube_writes(
            op.kube, policy=hub.policy("kube") if hub is not None else None)
        self._hook_consolidation_ledger(op)
        self.tune_operator(op)

    def tune_operator(self, op) -> None:
        """Determinism + speed tuning shared by every scenario flavor
        (faulted sweeps AND the crash drill's fault-free incarnations):
        compile-free solves, serialized worker pools, sub-ms batcher
        windows."""
        self._hook_solver(op)
        self._serialize_pools(op)
        self._shrink_batcher_windows(op)

    def install_crash(self) -> None:
        """Arm the process-wide crashpoint hook (crash drill). Crashpoints
        are plan sites like any other — `crash.<name>`, consulted by call
        index — so the kill is deterministic and respects paused()/enabled.
        SimulatedCrash derives BaseException: it sails past every
        `except Exception` cleanup fence exactly like a SIGKILL would, and
        the drill catches it on the drive stack."""
        from ..recovery import crashpoints

        def hook(site: str, _self=self):
            fault = _self.maybe(f"crash.{site}")
            if fault is not None:
                raise crashpoints.SimulatedCrash(site)

        crashpoints.install(hook)

    @staticmethod
    def uninstall_crash() -> None:
        from ..recovery import crashpoints

        crashpoints.uninstall()

    def _wrap_cloud_api(self, mocked_fn, site: str) -> None:
        orig = mocked_fn.default_fn

        def wrapped(request, _orig=orig, _site=site):
            fault = self.maybe(_site)
            if fault is not None:
                if fault.kind == KIND_CLOUD_TIMEOUT:
                    raise TimeoutError(f"chaos: {_site} timed out")
                raise cloud_errors.CloudError(
                    "InternalError", f"chaos: injected 5xx at {_site}")
            return _orig(request)

        mocked_fn.default_fn = wrapped

    def _wrap_kube_writes(self, kube, policy=None) -> None:
        """Emulate the httpkube transport's failure phases against the
        in-process store: request-phase means the write never applied;
        response-phase means it DID apply and only the ack was lost — the
        double-apply/retry class PR 1 hardened the real transport against.
        Event writes pass through unhooked: they are fire-and-forget
        observability traffic and would soak up every scheduled index.
        Intent-journal and configmap bookkeeping writes pass through too:
        they interleave with the object-plane writes the schedules were
        sampled against (shifting every index), and a faulted write-ahead
        record would break the exact recovery contract the crash drill's
        invariants assert."""
        skip_kinds = ("events", "intents", "configmaps")
        for method in ("create", "update", "delete", "bind_pod"):
            orig = getattr(kube, method)

            def wrapped(*args, _orig=orig, _method=method, _policy=policy,
                        **kwargs):
                if _method != "bind_pod" and args and args[0] in skip_kinds:
                    return _orig(*args, **kwargs)
                fault = self.maybe("kube.write")
                if fault is not None and fault.kind == KIND_KUBE_429:
                    # apiserver throttle: the write is REFUSED (never
                    # applied) and the server's Retry-After is honored
                    # through the kube edge's RetryPolicy — the same
                    # clamped sleep the real httpkube transport takes
                    # (virtual time under the chaos FakeClock)
                    if _policy is not None:
                        _policy.sleep_retry_after(KUBE_429_RETRY_AFTER_S)
                    raise ApiError(
                        429, f"chaos: {_method} throttled by the apiserver",
                        retry_after=KUBE_429_RETRY_AFTER_S)
                if fault is not None and fault.kind == KIND_KUBE_REQ_DISCONNECT:
                    raise ApiError(
                        0, f"chaos: connection lost before {_method} was sent")
                out = _orig(*args, **kwargs)
                if fault is not None and fault.kind == KIND_KUBE_RESP_DISCONNECT:
                    raise ApiError(
                        0, f"chaos: {_method} applied but the response was lost")
                return out

            setattr(kube, method, wrapped)

    def _hook_solver(self, op) -> None:
        # route_threshold=0 classifies every batch as "large" -> the
        # tpu rung (our crashing stand-in) runs first and its failures
        # exercise the real degrade chain
        op.provisioning.route_threshold = 0
        op.provisioning._solver_factory = (
            lambda catalog, provs: _ChaosSolver(catalog, provs, self))
        op.provisioning._solver_cache.clear()

    def _hook_consolidation_ledger(self, op) -> None:
        """Record every consolidation action WITH the disrupted nodes'
        prices at decision time — the cost invariant's evidence."""
        orig = op.deprovisioning._record_action

        def wrapped(action, now, label="", _orig=orig):
            prices = {}
            for name in action.nodes:
                node = op.cluster.nodes.get(name)
                if node is not None:
                    prices[name] = node.price
            self.consolidation_actions.append({
                "kind": action.kind,
                "nodes": list(action.nodes),
                "savings": action.savings,
                "replacement_price": (action.replacement[3]
                                      if action.replacement else None),
                "node_prices": prices,
            })
            return _orig(action, now, label=label)

        op.deprovisioning._record_action = wrapped

    def _serialize_pools(self, op) -> None:
        for obj, attr, prefix in ((op.provisioning, "_pool", "launch"),
                                  (op.interruption, "_pool", "interruption")):
            if obj is None:
                continue
            old = getattr(obj, attr)
            old.shutdown(wait=False)
            setattr(obj, attr, ThreadPoolExecutor(
                max_workers=1, thread_name_prefix=f"chaos-{prefix}"))

    def _shrink_batcher_windows(self, op) -> None:
        shrink_batcher_windows(op)

    # -- wire mode -------------------------------------------------------------

    def install_wire(self, server, cloud) -> None:
        """Hook the cloud-API server: a per-token launch ledger (proof the
        ClientToken dedupe holds) and the post-dispatch 5xx site — the
        fault that makes the dedupe load-bearing: the launch ran, the 500
        ate the response, the client retries the same token."""
        fleet_lock = threading.Lock()
        orig = server.dispatch

        def dispatch(action, payload, _orig=orig):
            if action != "CreateFleet":
                return _orig(action, payload)
            token = payload.get("client_token", "")
            with fleet_lock:  # serialize so launch attribution is exact
                before = cloud.create_fleet_api.called_with_count
                try:
                    out = _orig(action, payload)
                finally:
                    if token:
                        delta = (cloud.create_fleet_api.called_with_count
                                 - before)
                        self.token_launches[token] = (
                            self.token_launches.get(token, 0) + delta)
                fault = self.maybe("wire.create_fleet")
                if fault is not None:
                    raise RuntimeError(
                        "chaos: connection dropped after CreateFleet "
                        "dispatched")
                return out

        server.dispatch = dispatch

    # -- cycle-driven faults ---------------------------------------------------

    def on_cycle(self, op, cloud, cycle: int) -> "list[str]":
        """Consult every cycle site once; returns the kinds applied (the
        runner logs them). Also expires previously injected ICE pools."""
        applied = []
        for pool, expires in list(self._ice_expiry.items()):
            if cycle >= expires:
                cloud.insufficient_capacity_pools.discard(pool)
                del self._ice_expiry[pool]
        if self._mem_expiry is not None and cycle >= self._mem_expiry:
            from .. import overload

            overload.set_simulated_rss(None)
            self._mem_expiry = None
        for site in sorted(planmod.CYCLE_SITES):
            fault = self.maybe(site)
            if fault is None:
                continue
            if fault.kind == KIND_CLOUD_ICE:
                self._inject_ice(cloud, cycle, fault)
            elif fault.kind == KIND_SPOT_BURST:
                self._inject_spot_burst(op, cloud, fault)
            elif fault.kind == KIND_CLOCK_SKEW:
                op.clock.step(fault.param)
            elif fault.kind == KIND_KUBE_WATCH_RESET:
                self._inject_watch_reset(op)
            elif fault.kind == KIND_HOST_MEM_PRESSURE:
                self._inject_mem_pressure(cycle, fault)
            elif fault.kind == KIND_WATCH_FLOOD:
                # a flood is N resets back to back: the relist echo storm,
                # amplified — every watcher absorbs param× the object churn
                for _ in range(int(fault.param)):
                    self._inject_watch_reset(op)
            applied.append(fault.kind)
        return applied

    def _inject_ice(self, cloud, cycle: int, fault) -> None:
        if cloud.catalog is None or not cloud.catalog.types:
            return
        itype = self._cycle_rng.choice(
            sorted(t.name for t in cloud.catalog.types))
        zone = self._cycle_rng.choice(
            sorted(s.zone for s in cloud.subnets))
        ct = self._cycle_rng.choice(
            (wk.CAPACITY_TYPE_ON_DEMAND, wk.CAPACITY_TYPE_SPOT))
        pool = (ct, itype, zone)
        cloud.insufficient_capacity_pools.add(pool)
        self._ice_expiry[pool] = cycle + int(fault.param)

    def _inject_spot_burst(self, op, cloud, fault) -> None:
        if op.interruption is None:
            return
        with cloud.lock:
            spot = sorted(i.id for i in cloud.instances.values()
                          if i.state == "running"
                          and i.capacity_type == wk.CAPACITY_TYPE_SPOT)
        for _ in range(int(fault.param)):
            if not spot:
                break
            iid = spot.pop(self._cycle_rng.next_u64() % len(spot))
            op.queue.send(json.dumps({
                "source": "cloud.spot",
                "detail-type": "Spot Instance Interruption Warning",
                "detail": {"instance-id": iid}}))

    def _inject_mem_pressure(self, cycle: int, fault) -> None:
        """Pin the overload plane's simulated host RSS at the cap for
        `param` cycles. The simulation hook is deliberately plane-global
        (guards read it whether or not the plane is enabled) — the strict
        noop audit needs the DISABLED plane to see identical inputs and
        still do nothing."""
        from .. import overload

        overload.set_simulated_rss(MEM_PRESSURE_RSS_BYTES)
        self._mem_expiry = cycle + int(fault.param)

    def _inject_watch_reset(self, op) -> None:
        """A dropped watch stream forces a relist, and the relist replays
        every object as 'modified' — the echo storm every watcher must
        absorb without corrupting derived state."""
        kube = op.kube
        for kind in kube.KINDS:
            with kube._lock:
                objs = sorted(kube._objects[kind].items())
            for _name, obj in objs:
                kube._notify(kind, "modified", obj)
