"""Deterministic chaos plane: seeded fault injection + safety invariants.

See docs/designs/chaos.md. Entry points:

    python -m karpenter_tpu chaos --seed 7 --scenarios 3
    make chaos
"""

from .inject import ChaosInjector
from .invariants import Violation, check_all
from .plan import (CALL_SITES, CYCLE_SITES, LAYER_OF_KIND, SITES, ChaosRng,
                   FaultPlan, FaultSpec)
from .runner import ChaosRunner

__all__ = [
    "CALL_SITES", "CYCLE_SITES", "LAYER_OF_KIND", "SITES",
    "ChaosInjector", "ChaosRng", "ChaosRunner", "FaultPlan", "FaultSpec",
    "Violation", "check_all",
]
