"""Chaos scenario driver: seed -> plan -> faulted convergence -> verdict.

One scenario assembles a fresh hermetic operator (FakeClock, FakeCloud,
in-process kube store), installs the injector, and drives reconcile
cycles in two phases:

  chaos phase   — CHAOS_CYCLES cycles with faults armed. Every cycle
                  consults the cycle sites (ICE, spot burst, clock skew,
                  watch reset), runs each controller once (exceptions
                  logged, never fatal — crashing on an injected fault is
                  itself a finding), and lets the workload "ReplicaSet"
                  replace drained pods.
  settle phase  — faults disarmed; cycles continue until quiescence or a
                  step deadline, then the clock jumps past the GC grace
                  window so leak reaping can run, then a final settle.

After convergence the cross-layer invariants run and the scenario emits
a JSON-serializable dict. Everything inside a scenario dict is a pure
function of (seed, scenario) — that is the replay contract the tests
assert — so volatile fields (wall-clock duration) live only at the
artifact top level.

Crash mode (`crash=True`, `make chaos-crash`) swaps the fault sweep for
the crash–restart drill: one scenario per named crashpoint
(recovery/crashpoints.py) kills incarnation 1 mid-action via
SimulatedCrash, discards its object graph while the kube/cloud/queue
stores survive, boots a fresh operator against those stores, and runs
the recovery sequence (epoch mint -> hydration -> journal replay) plus
the recovery invariants — exactly-once launch, journal-resolved-within-K,
no orphans, write-ahead ordering. A final two-replica scenario drives a
leader crash through the real LeaderElector and proves fencing rejects
the zombie ex-leader's late writes.
"""

from __future__ import annotations

import json
import logging
import os
import time

from ..apis import wellknown as wk
from ..apis.nodetemplate import NodeTemplate
from ..apis.provisioner import Provisioner
from ..apis.settings import Settings
from ..fake.cloud import FakeCloud
from ..models import machine as machine_model
from ..models.instancetype import Catalog, make_instance_type
from ..models.pod import make_pod
from ..models.requirements import OP_IN, Requirements
from ..operator import Operator
from ..utils.clock import FakeClock
from . import invariants
from .plan import LAYER_OF_KIND, ChaosRng, FaultPlan
from .inject import ChaosInjector, shrink_batcher_windows

log = logging.getLogger("karpenter.chaos")


def chaos_catalog() -> Catalog:
    """Small mixed catalog: enough shape diversity for consolidation to
    have real choices, small enough that scenarios stay fast."""
    return Catalog(types=[
        make_instance_type("t.small", cpu=2, memory="2Gi",
                           od_price=0.05, spot_price=0.02),
        make_instance_type("m.large", cpu=4, memory="16Gi",
                           od_price=0.20, spot_price=0.07),
        make_instance_type("m.xlarge", cpu=16, memory="64Gi",
                           od_price=0.80, spot_price=0.28),
    ])


class _StubReplica:
    """In-process fleet replica for the partition drill: serves solves in
    a deterministic per-replica base latency, and fails in exactly the
    three shapes the failover plane distinguishes — dead (refused fast),
    partitioned (blackholed: the caller burns its whole deadline), slow
    (gray: answers, late). Service time advances the FakeClock, so the
    drill's p99 ledger and the membership detectors see the same physics.
    A poison request crashes whichever replica serves it."""

    SLOW_FACTOR = 20.0     # gray replica: ~20x its healthy latency
    REFUSED_S = 0.0001     # a connection refused is near-instant

    def __init__(self, name: str, base_latency_s: float, clock):
        self.name = name
        self.base_latency_s = base_latency_s
        self.clock = clock
        self.state = "ok"   # ok | dead | partitioned | slow
        self.synced: "set[str]" = set()   # tenants with a warm catalog
        self.served = 0

    def probe(self) -> float:
        """Health surface for the MembershipManager: returns the probe
        latency (gray evidence) or raises (missed beat)."""
        from ..fleet import ReplicaTimeout, ReplicaUnavailable

        if self.state == "dead":
            raise ReplicaUnavailable(self.name, "connection refused")
        if self.state == "partitioned":
            raise ReplicaTimeout(self.name, "probe blackholed")
        if self.state == "slow":
            return self.base_latency_s * self.SLOW_FACTOR
        return self.base_latency_s

    def solve(self, tenant_id: str, request, timeout_s):
        from ..fleet import (ReplicaCrashed, ReplicaTimeout,
                             ReplicaUnavailable)

        if self.state == "dead":
            self.clock.step(self.REFUSED_S)
            raise ReplicaUnavailable(self.name, "connection refused")
        if self.state == "partitioned":
            # blackhole: nothing answers, the caller waits out its deadline
            self.clock.step(timeout_s if timeout_s else 1.0)
            raise ReplicaTimeout(self.name, "request blackholed")
        if isinstance(request, dict) and request.get("poison"):
            self.clock.step(self.base_latency_s)
            self.state = "dead"   # the request killed its server
            raise ReplicaCrashed(self.name, "replica died serving request")
        latency = self.base_latency_s * (
            self.SLOW_FACTOR if self.state == "slow" else 1.0)
        if timeout_s is not None and latency > timeout_s:
            self.clock.step(timeout_s)
            raise ReplicaTimeout(
                self.name, f"{latency:.4f}s exceeds {timeout_s:.4f}s "
                "deadline")
        self.clock.step(latency)
        self.served += 1
        return {"tenant": tenant_id, "replica": self.name,
                "pods": request.get("pods", 0)
                if isinstance(request, dict) else 0}


class ChaosRunner:
    CHAOS_CYCLES = 18          # > FaultPlan.CYCLE_HORIZON so every cycle fault can land
    SETTLE_DEADLINE = 30       # settle cycles before declaring non-quiescence
    CYCLE_SECONDS = 30.0

    def __init__(self, seed: int, scenarios: int = 1, wire: bool = False,
                 intensity: float = 1.0, out_dir: "str | None" = None,
                 burst: bool = False, crash: bool = False,
                 storm: bool = False, partition: bool = False,
                 spot_storm: bool = False,
                 spot_storm_nodes: "int | None" = None,
                 spot_storm_reclaims: "int | None" = None):
        self.seed = seed
        self.scenarios = scenarios
        self.wire = wire
        self.intensity = intensity
        self.out_dir = out_dir
        # burst mode swaps the sampled schedule for FaultPlan.burst — the
        # dense cloud-5xx + solver-crash window that exercises the
        # resilience plane (breakers, budgets, ladders) hard enough for
        # its invariants to have teeth
        self.burst = burst
        # crash mode runs the crash–restart recovery drill instead of the
        # fault sweep (one scenario per crashpoint + the failover drill)
        self.crash = crash
        # storm mode runs the multi-tenant fleet admission drill: one hot
        # tenant bursting against light tenants through a FleetFrontend
        # with a deterministic stub backend, asserting the fairness
        # invariant (no tenant waits past the starvation bound) and that
        # both shed paths (admission, queue) actually fire
        self.storm = storm
        # partition mode runs the multi-replica fleet failover drill:
        # replica kill, blackhole partition, gray slow-replica, poison
        # request and rejoin against a MembershipManager + FailoverClient,
        # auditing remap blast radius, completes-or-sheds, quarantine
        # cascade bounds and membership epoch monotonicity
        self.partition = partition
        # spot-storm mode runs the 10k-node reclaim-storm drill: a large
        # mostly-spot fleet, a live interruption forecast, a proactive
        # rebalance window, then thousands of simultaneous reclaim
        # warnings in ONE tick — auditing cost-never-raised,
        # capacity-restored-within-K, rebalance-never-strands and the
        # quarantine/forecast composition, plus the forecaster-was-wrong
        # adversarial schedule and the strict-noop decision-parity half
        self.spot_storm = spot_storm
        self.spot_storm_nodes = spot_storm_nodes
        self.spot_storm_reclaims = spot_storm_reclaims
        # diagnostics bundles auto-dumped by failed scenarios (volatile:
        # paths depend on out_dir, so they live at the artifact top level,
        # never inside a scenario dict)
        self._bundles: "list[str]" = []

    # -- assembly --------------------------------------------------------------

    def _build(self, clock: FakeClock, kube=None, cloud=None, queue=None,
               leader_elect: bool = False, identity: "str | None" = None,
               name_suffix: "str | None" = None):
        """Assemble an operator. Passing surviving `kube`/`cloud`/`queue`
        stores is the crash drill's rebirth: the object graph is brand new,
        the durable state is whatever the dead incarnation left behind —
        so the nodetemplate/provisioner bootstrap writes are guarded.
        `name_suffix` replaces the random machine-name suffix: the crash
        artifact embeds machine names (journal keys, replay ledger), so the
        drill pins a deterministic, per-incarnation-unique one."""
        catalog = cloud.catalog if cloud is not None else chaos_catalog()
        if cloud is None:
            cloud = FakeCloud(catalog=catalog, clock=clock)
        settings = Settings(cluster_name="chaos",
                            cluster_endpoint="https://chaos.example",
                            batch_idle_duration=0.0, batch_max_duration=0.0,
                            interruption_queue_name="chaos-q")
        op = Operator(cloud, settings, catalog, kube=kube, clock=clock,
                      queue=queue, leader_elect=leader_elect,
                      identity=identity)
        if name_suffix:
            op.provisioning._name_suffix = name_suffix
        if op.kube.get("nodetemplates", "default") is None:
            op.kube.create("nodetemplates", "default", NodeTemplate(
                name="default",
                subnet_selector={
                    "id": "subnet-zone-1a,subnet-zone-1b,subnet-zone-1c"},
                security_group_selector={"id": "sg-default"}))
        op.cloudprovider.register_nodetemplate(
            op.kube.get("nodetemplates", "default"))
        if op.kube.get("provisioners", "default") is None:
            op.kube.create("provisioners", "default",
                           self._chaos_provisioner())
        return op, cloud

    def _chaos_provisioner(self, instance_types=None,
                           capacity_types=None,
                           consolidation: bool = True) -> Provisioner:
        reqs = [(wk.LABEL_CAPACITY_TYPE, OP_IN,
                 list(capacity_types) if capacity_types else
                 [wk.CAPACITY_TYPE_SPOT, wk.CAPACITY_TYPE_ON_DEMAND])]
        if instance_types:
            reqs.append((wk.LABEL_INSTANCE_TYPE, OP_IN,
                         list(instance_types)))
        prov = Provisioner(
            name="default", provider_ref="default",
            consolidation_enabled=consolidation,
            requirements=Requirements.of(*reqs))
        prov.set_defaults()
        prov.validate()
        return prov

    def _workload(self, plan: FaultPlan) -> "dict[str, dict]":
        """Derive the steady workload from the plan's PRNG family so every
        scenario stresses a different shape — deterministically."""
        r = ChaosRng((plan.seed << 8) ^ plan.scenario).fork("workload")
        n = r.randint(6, 12)
        sizes = (("1", "2Gi"), ("2", "4Gi"), ("500m", "1Gi"))
        return {f"w{i}": {"cpu": c, "memory": m}
                for i in range(n)
                for c, m in (r.choice(sizes),)}

    def _reconcile_workload(self, op, workload, injector) -> None:
        """ReplicaSet analogue: pods drained by termination (the store
        deletes them) or orphaned on a reaped node come back as fresh
        unbound pods. Harness traffic must not consume fault indices."""
        with injector.paused():
            for name, shape in workload.items():
                obj = op.kube.get("pods", name)
                if obj is not None and obj.node_name \
                        and obj.node_name not in op.cluster.nodes:
                    op.kube.delete("pods", name)
                    obj = None
                if obj is None:
                    op.kube.create("pods", name, make_pod(name, **shape))

    # -- driving ---------------------------------------------------------------

    _CONTROLLERS = ("settingswatch", "nodetemplate", "machinehydration",
                    "provisioning", "machinelifecycle", "interruption",
                    "deprovisioning", "termination", "counters",
                    "garbagecollection")

    def _drive_once(self, op, errors: "list[str]") -> None:
        """reconcile_all_once + GC, but each controller individually
        fenced: an injected fault escaping a controller's own error
        handling is recorded, not fatal."""
        for name in self._CONTROLLERS:
            ctrl = getattr(op, name)
            if ctrl is None:
                continue
            try:
                ctrl.reconcile_once()
            except Exception as e:  # noqa: BLE001 — the fence is the point
                errors.append(f"{name}: {type(e).__name__}: {e}")
        # the spot plane rides every drive like the operator's own loop
        # (forecast refresh + proactive rebalance; strict noop when the
        # plane is disabled) — skipping it would trip the spotrebalance
        # deadman the Operator registers unconditionally
        try:
            op._spot_tick()
        except Exception as e:  # noqa: BLE001
            errors.append(f"spotrebalance: {type(e).__name__}: {e}")
        # introspection rides every drive: the flight recorder's snapshot
        # ring gets per-cycle history and the deadman sees crash-looping
        # controllers (their failed cycles never refresh the heartbeat)
        op.flightrecorder.record_snapshot()
        op.watchdog.check()

    def _quiescent(self, op) -> bool:
        if op.kube.pending_pods():
            return False
        if any(n.marked_for_deletion for n in op.cluster.nodes.values()):
            return False
        if getattr(op.deprovisioning, "_pending_replace", None):
            return False
        for m in op.kube.machines():
            if m.status.state != machine_model.INITIALIZED:
                return False
        return True

    # -- one scenario ----------------------------------------------------------

    def run_scenario(self, scenario: int) -> dict:
        if self.burst:
            plan = FaultPlan.burst(self.seed, scenario)
        else:
            plan = FaultPlan.from_seed(self.seed, scenario,
                                       wire=False, intensity=self.intensity)
        injector = ChaosInjector(plan)
        clock = FakeClock()
        op, cloud = self._build(clock)
        # retry backoffs must advance the FAKE clock: a real time.sleep
        # under FakeClock would deadlock the single-threaded drive
        op.resilience.use_virtual_sleep()
        workload = self._workload(plan)
        errors: "list[str]" = []
        # profiling-strict-noop drill: the profiling plane is disabled for
        # the whole scenario (including --burst, which shares this path)
        # and its activity counters are diffed at the end — any growth
        # means a producer ignored the switch (invariants.py)
        from .. import profiling
        prof_prev = profiling.set_enabled(False)
        prof_before = profiling.activity()
        # explain-strict-noop drill: same contract for the decision-
        # provenance plane — disabled for the whole scenario, activity
        # diffed at the end (invariants.check_explain_noop). The --storm
        # drill is the complement: it runs with explain ON and asserts
        # every shed cites a vocabulary reason.
        from .. import explain
        expl_prev = explain.set_enabled(False)
        expl_before = explain.activity()
        # membership-strict-noop drill: third plane, same contract — the
        # sweep runs with health-gated membership off and any activity
        # delta (a probe, a transition, an epoch bump) is a violation;
        # the --partition drill is where the plane runs hot
        from ..fleet import membership as fleet_membership
        mem_prev = fleet_membership.set_enabled(False)
        mem_before = fleet_membership.activity()
        # incremental plane: TWO windows. The chaotic cycles run with the
        # plane ON — every reconcile's dirty-subproblem solve carries its
        # bit-parity audit, and the audit_divergences delta is the
        # incremental-parity-never-diverges evidence. The settle phase
        # then flips it OFF for the strict-noop diff (same contract as
        # profiling/explain/membership).
        from .. import incremental
        inc_prev = incremental.set_enabled(True)
        inc_parity_before = incremental.activity()
        try:
            injector.install(op, cloud)
            self._reconcile_workload(op, workload, injector)
            for cycle in range(self.CHAOS_CYCLES):
                injector.on_cycle(op, cloud, cycle)
                self._drive_once(op, errors)
                self._reconcile_workload(op, workload, injector)
                clock.step(self.CYCLE_SECONDS)
            inc_parity_after = incremental.activity()
            incremental.set_enabled(False)
            inc_noop_before = incremental.activity()

            # settle: disarm, clear injected weather, converge
            injector.enabled = False
            for pool in list(injector._ice_expiry):
                cloud.insufficient_capacity_pools.discard(pool)
            injector._ice_expiry.clear()
            # a still-armed host-memory-pressure fault is weather too: its
            # expiry cycle may lie past CHAOS_CYCLES, and a leaked
            # simulated RSS would poison the overload probe windows below
            # (and every later scenario in this process)
            from .. import overload as overload_plane
            if injector._mem_expiry is not None:
                overload_plane.set_simulated_rss(None)
                injector._mem_expiry = None
            settle_cycles = 0
            for _ in range(self.SETTLE_DEADLINE):
                settle_cycles += 1
                self._drive_once(op, errors)
                self._reconcile_workload(op, workload, injector)
                clock.step(self.CYCLE_SECONDS)
                if self._quiescent(op):
                    break
            # leak reaping: jump past the GC grace window twice (both GC
            # directions carry their own eventual-consistency window),
            # then a short post-GC settle for any termination it queued
            for _ in range(2):
                clock.step(360.0)
                self._drive_once(op, errors)
            for _ in range(6):
                self._drive_once(op, errors)
                self._reconcile_workload(op, workload, injector)
                clock.step(self.CYCLE_SECONDS)
                if self._quiescent(op):
                    break

            # resilience-plane evidence (breaker ledgers, budget water
            # marks, ladder transitions) — captured before stop() and fed
            # to the structural invariants
            resilience_evidence = op.resilience.evidence()
            prof_after = profiling.activity()
            profiling_evidence = {
                "enabled": False,
                "before": prof_before,
                "after": prof_after,
            }
            # the replayed scenario dict stores only the DELTAS (all zero
            # when the noop invariant holds): the absolute counters depend
            # on whatever ran in this process before the scenario, and the
            # replay contract says the dict is a pure function of the seed
            profiling_stored = {
                "enabled": False,
                "deltas": {k: prof_after[k] - prof_before[k]
                           for k in prof_before},
            }
            # critical plane: probe-based TWO-window evidence, run after
            # prof_after is captured so the probe's own gap-ledger rows
            # cannot disturb the profiling-noop diff. The enabled window
            # proves the plane records when on (producers wired); the
            # noop window proves KARPENTER_TPU_CRITICAL=0 moves zero
            # counters and leaves the interval ring empty (strict-noop,
            # invariants.check_critical_noop).
            from ..profiling import GAP_LEDGER
            from ..profiling import critical as critical_plane

            def _critical_probe():
                with GAP_LEDGER.solve_scope("chaos_probe"):
                    GAP_LEDGER.note("encode", 1e-4, lane="encode")
                    GAP_LEDGER.note("device_exec", 1e-4, lane="device")
                    GAP_LEDGER.note_wait("queue_wait", 1e-4, lane="tick")

            crit_prof_prev = profiling.set_enabled(True)
            crit_prev = critical_plane.set_enabled(True)
            crit_on_before = critical_plane.activity()
            _critical_probe()
            _critical_probe()
            crit_on_after = critical_plane.activity()
            critical_plane.set_enabled(False)
            crit_off_before = critical_plane.activity()
            _critical_probe()
            _critical_probe()
            crit_off_after = critical_plane.activity()
            critical_plane.set_enabled(crit_prev)
            profiling.set_enabled(crit_prof_prev)
            critical_evidence = {
                "enabled": {"enabled": True,
                            "before": crit_on_before,
                            "after": crit_on_after},
                "noop": {"enabled": False,
                         "before": crit_off_before,
                         "after": crit_off_after},
            }
            # stored enabled-window deltas carry only the MONOTONIC
            # counters: the ring-length delta is not a pure function of
            # the seed once the ring is at capacity, and the replay
            # contract forbids nondeterministic fields
            _crit_monotone = ("records_total", "intervals_total",
                              "wait_notes_total")
            critical_stored = {
                "enabled": {"enabled": True,
                            "deltas": {k: crit_on_after[k]
                                       - crit_on_before[k]
                                       for k in _crit_monotone}},
                "noop": {"enabled": False,
                         "deltas": {k: crit_off_after[k]
                                    - crit_off_before[k]
                                    for k in crit_off_before}},
            }
            # spot plane: TWO probe windows after the scenario, same shape
            # as the critical plane. The sweep itself runs with the plane
            # at its default — advisory, ledger/static rung, below the
            # rebalance threshold, so it never steers a solve. The enabled
            # window proves the producers are wired (a refresh, a rate
            # lookup and a rebalance reconcile all move counters); the
            # disabled window drives the same surface and any counter
            # growth is a spot-strict-noop violation. The --spot-storm
            # drill is the complement where the plane runs hot.
            from .. import spot as spot_plane

            def _spot_probe():
                op.spotforecaster.refresh()
                op.spotforecaster.rate("t.small", "zone-1a", "spot")
                op.spotforecaster.penalty("t.small", "zone-1a", "spot")
                if op.spotrebalance is not None:
                    op.spotrebalance.reconcile_once()

            spot_prev = spot_plane.set_enabled(True)
            spot_on_before = spot_plane.activity()
            _spot_probe()
            _spot_probe()
            spot_on_after = spot_plane.activity()
            spot_plane.set_enabled(False)
            spot_off_before = spot_plane.activity()
            _spot_probe()
            _spot_probe()
            spot_off_after = spot_plane.activity()
            spot_plane.set_enabled(spot_prev)
            spot_evidence = {
                "enabled": {"enabled": True,
                            "before": spot_on_before,
                            "after": spot_on_after},
                "noop": {"enabled": False,
                         "before": spot_off_before,
                         "after": spot_off_after},
            }
            # enabled-window stored deltas carry only the counters the
            # probe touches deterministically (ladder fallbacks depend on
            # sticky rung state, which the replay contract must not see)
            _spot_monotone = ("spot_forecast_refreshes",
                              "spot_forecasts_computed",
                              "spot_rebalance_cycles")
            spot_stored = {
                "enabled": {"enabled": True,
                            "deltas": {k: spot_on_after[k]
                                       - spot_on_before[k]
                                       for k in _spot_monotone}},
                "noop": {"enabled": False,
                         "deltas": {k: spot_off_after[k]
                                    - spot_off_before[k]
                                    for k in spot_off_before}},
            }
            # overload plane: the same two-window probe shape. A chaos
            # scenario never runs a fleet frontend, so the backpressure
            # surface needs a dedicated probe: a fresh guard spiked to
            # brownout by simulated host pressure, then recovered; a
            # fresh admission filter offered a repeat-sighting catalog
            # hash. The enabled window proves the producers count; the
            # disabled window drives the IDENTICAL surface and any
            # counter growth — or any decide() verdict other than
            # "accept" — is an overload-strict-noop violation. The
            # churn drill is the complement where the plane runs hot.

            def _overload_probe():
                guard = overload_plane.OverloadGuard(
                    clock=op.clock, rss_soft_cap=1 << 30)
                admission = overload_plane.AdmissionFilter()
                decisions = []
                try:
                    overload_plane.set_simulated_rss(2 << 30)  # 2x the cap
                    guard.observe(backlog=1.0, deadline=0.8)
                    decisions.append(guard.decide(over_rate=True))
                    overload_plane.set_simulated_rss(0)
                    guard.observe()  # pressure gone -> one-step recovery
                    decisions.append(guard.decide(over_rate=False))
                    admission.offer("probe-hash-a")
                    admission.offer("probe-hash-a")  # second sighting earns
                    admission.offer("probe-hash-b")
                finally:
                    overload_plane.set_simulated_rss(None)
                return decisions

            ov_prev = overload_plane.set_enabled(True)
            ov_on_before = overload_plane.activity()
            _overload_probe()
            _overload_probe()
            ov_on_after = overload_plane.activity()
            overload_plane.set_enabled(False)
            ov_off_before = overload_plane.activity()
            ov_off_decisions = _overload_probe() + _overload_probe()
            ov_off_after = overload_plane.activity()
            overload_plane.set_enabled(ov_prev)
            overload_evidence = {
                "enabled": {"enabled": True,
                            "before": ov_on_before,
                            "after": ov_on_after},
                "noop": {"enabled": False,
                         "before": ov_off_before,
                         "after": ov_off_after,
                         "decisions": ov_off_decisions},
            }
            # probe guards/filters are constructed fresh each call, so
            # every enabled-window delta is a pure function of the probe
            # (unlike spot's sticky ladder) — the stored dict carries
            # them all
            overload_stored = {
                "enabled": {"enabled": True,
                            "deltas": {k: ov_on_after[k]
                                       - ov_on_before[k]
                                       for k in ov_on_before}},
                "noop": {"enabled": False,
                         "deltas": {k: ov_off_after[k]
                                    - ov_off_before[k]
                                    for k in ov_off_before},
                         "decisions": ov_off_decisions},
            }
            expl_after = explain.activity()
            explain_evidence = {
                "enabled": False,
                "before": expl_before,
                "after": expl_after,
            }
            explain_stored = {
                "enabled": False,
                "deltas": {k: expl_after[k] - expl_before[k]
                           for k in expl_before},
            }
            mem_after = fleet_membership.activity()
            membership_evidence = {
                "enabled": False,
                "before": mem_before,
                "after": mem_after,
            }
            membership_stored = {
                "enabled": False,
                "deltas": {k: mem_after[k] - mem_before[k]
                           for k in mem_before},
            }
            inc_noop_after = incremental.activity()
            incremental_evidence = {
                "parity": {"enabled": True,
                           "before": inc_parity_before,
                           "after": inc_parity_after},
                "noop": {"enabled": False,
                         "before": inc_noop_before,
                         "after": inc_noop_after},
            }
            incremental_stored = {
                "parity": {"enabled": True,
                           "deltas": {k: inc_parity_after[k]
                                      - inc_parity_before[k]
                                      for k in inc_parity_before}},
                "noop": {"enabled": False,
                         "deltas": {k: inc_noop_after[k]
                                    - inc_noop_before[k]
                                    for k in inc_noop_before}},
            }
            violations = invariants.check_all(
                op, cloud,
                token_launches=injector.token_launches,
                consolidation_actions=injector.consolidation_actions,
                resilience=resilience_evidence,
                profiling=profiling_evidence,
                explain=explain_evidence,
                membership=membership_evidence,
                incremental=incremental_evidence,
                critical=critical_evidence,
                spot=spot_evidence,
                overload=overload_evidence)
            if not self._quiescent(op):
                violations = [invariants.Violation(
                    "quiescence",
                    "scenario never reached quiescence before the step "
                    "deadline")] + violations
            # a failed seed dumps a diagnostics bundle next to its replay
            # artifact: the snapshot ring, logs, traces and events from the
            # exact cycles that broke the invariant (deterministic path —
            # replaying the seed overwrites the same file)
            if violations and self.out_dir:
                os.makedirs(self.out_dir, exist_ok=True)
                bundle_path = os.path.join(
                    self.out_dir,
                    f"chaos_seed{self.seed}_s{scenario}_bundle.json")
                written = op.flightrecorder.trigger(
                    "chaos_invariant_breach",
                    detail="; ".join(
                        f"[{v.invariant}] {v.message}"
                        for v in violations)[:500],
                    force=True, path=bundle_path)
                if written:
                    self._bundles.append(written)
        finally:
            profiling.set_enabled(prof_prev)
            explain.set_enabled(expl_prev)
            fleet_membership.set_enabled(mem_prev)
            incremental.set_enabled(inc_prev)
            # never let a simulated RSS escape this scenario, even on the
            # exception path (the settle-phase clear may not have run)
            from .. import overload as _overload
            _overload.set_simulated_rss(None)
            op.stop()

        fired_kinds = sorted(injector.fired_kinds())
        return {
            "seed": self.seed,
            "scenario": scenario,
            "workload_pods": len(workload),
            "plan": plan.describe(),
            "fired": list(injector.fired),
            "site_counts": injector.site_counts(),
            "fired_kinds": fired_kinds,
            "layers": sorted({LAYER_OF_KIND[k] for k in fired_kinds}),
            "controller_errors": errors,
            "consolidation_actions": len(injector.consolidation_actions),
            "settle_cycles": settle_cycles,
            "final_nodes": len(op.cluster.nodes),
            "resilience": resilience_evidence,
            "profiling": profiling_stored,
            "explain": explain_stored,
            "membership": membership_stored,
            "incremental": incremental_stored,
            "critical": critical_stored,
            "spot": spot_stored,
            "overload": overload_stored,
            "violations": [v.as_dict() for v in violations],
            "passed": not violations,
        }

    # -- crash drill -----------------------------------------------------------

    CRASH_MAX_CYCLES = 24  # cycles granted for the crashpoint to be reached

    # crashpoints the initial workload's own provisioning walks into; the
    # teardown-family sites need a settled cluster plus one staged action
    _LAUNCH_SITES = ("fleet.pre_dispatch", "launch.pre_register",
                     "launch.mid_bind")

    def _crash_workload(self, site: str, plan: FaultPlan) -> "dict[str, dict]":
        if site == "deprovisioning.mid_replace":
            # one small pod pinned onto m.large: widening the provisioner
            # later makes the t.small replace a certainty, so the staged
            # consolidation deterministically reaches the crashpoint
            return {"w0": {"cpu": "500m", "memory": "1Gi"}}
        return self._workload(plan)

    def _stage_crash_trigger(self, op, cloud, site: str, injector) -> bool:
        """Stage the action that walks into the armed crashpoint. Returns
        True once staged (launch-family sites need nothing staged)."""
        if site in self._LAUNCH_SITES:
            return True
        if not self._quiescent(op):
            return False
        with injector.paused():
            if site == "termination.mid_delete":
                op.termination.request_deletion(sorted(op.cluster.nodes)[0])
            elif site == "deprovisioning.mid_replace":
                # widen the pinned provisioner: consolidation now sees the
                # cheaper t.small and stages a replace
                op.kube.update("provisioners", "default",
                               self._chaos_provisioner())
            elif site == "interruption.pre_ack":
                with cloud.lock:
                    running = sorted(i.id for i in cloud.instances.values()
                                     if i.state == "running")
                op.queue.send(json.dumps({
                    "source": "cloud.spot",
                    "detail-type": "Spot Instance Interruption Warning",
                    "detail": {"instance-id": running[0]}}))
            elif site == "spot.mid_rebalance":
                # storm the pool the first spot node sits in: the next
                # forecast refresh consumes the injected live schedule,
                # the rebalance controller banks the at-risk mass,
                # launches the replacement, and walks into the crashpoint
                # between the journal re-record and the phase-2 drain
                spot_nodes = [
                    op.cluster.nodes[n] for n in sorted(op.cluster.nodes)
                    if op.cluster.nodes[n].capacity_type ==
                    wk.CAPACITY_TYPE_SPOT
                    and op.cluster.nodes[n].initialized]
                if not spot_nodes:
                    return False
                target = spot_nodes[0]
                schedule = {(target.instance_type, target.zone,
                             wk.CAPACITY_TYPE_SPOT): 0.9}
                op.spotforecaster.set_live_source(lambda: dict(schedule))
        return True

    def _recover_and_settle(self, op2, workload, injector, clock,
                            errors) -> "tuple[list, list, int]":
        """The reborn operator's first breaths, exactly as start() runs
        them: epoch mint -> machine hydration -> journal replay, then the
        replay-budget window, then settle + GC. Returns (replay ledger,
        stale-records-after-budget, settle cycles)."""
        from ..recovery import RecoveryManager

        epoch = op2.recovery.begin_incarnation()
        op2.machinehydration.reconcile_once()
        replay = op2.recovery.replay()
        for _ in range(RecoveryManager.REPLAY_BUDGET_CYCLES):
            self._drive_once(op2, errors)
            self._reconcile_workload(op2, workload, injector)
            clock.step(self.CYCLE_SECONDS)
        stale = [r.name for r in op2.journal.pending(before_epoch=epoch)]
        settle_cycles = 0
        for _ in range(self.SETTLE_DEADLINE):
            settle_cycles += 1
            self._drive_once(op2, errors)
            self._reconcile_workload(op2, workload, injector)
            clock.step(self.CYCLE_SECONDS)
            if self._quiescent(op2):
                break
        for _ in range(2):
            clock.step(360.0)
            self._drive_once(op2, errors)
        for _ in range(6):
            self._drive_once(op2, errors)
            self._reconcile_workload(op2, workload, injector)
            clock.step(self.CYCLE_SECONDS)
            if self._quiescent(op2):
                break
        return replay, stale, settle_cycles

    def _crash_verdict(self, op2, cloud, site, crash, pending_at_rebirth,
                       stale_after_budget) -> "list":
        from ..recovery import RecoveryManager

        violations = invariants.check_all(
            op2, cloud, resilience=op2.resilience.evidence())
        violations += invariants.check_exactly_once_launch(cloud)
        violations += invariants.check_journal_resolved(op2)
        if crash is None:
            violations.append(invariants.Violation(
                "crashpoint-reached",
                f"crashpoint {site} was never reached — the drill proved "
                "nothing"))
        if not pending_at_rebirth:
            violations.append(invariants.Violation(
                "journal-write-ahead",
                f"no intent record was pending when the process died at "
                f"{site} — the write-ahead ordering is broken"))
        if stale_after_budget:
            violations.append(invariants.Violation(
                "journal-replay-budget",
                f"prior-epoch records {stale_after_budget} still pending "
                f"{RecoveryManager.REPLAY_BUDGET_CYCLES} cycles after "
                "replay"))
        if not self._quiescent(op2):
            violations.insert(0, invariants.Violation(
                "quiescence",
                "reborn operator never reached quiescence before the step "
                "deadline"))
        return violations

    def _crash_bundle(self, op2, scenario: int, tag: str, violations) -> None:
        if not (violations and self.out_dir):
            return
        os.makedirs(self.out_dir, exist_ok=True)
        path = os.path.join(
            self.out_dir,
            f"chaos_crash_seed{self.seed}_s{scenario}_bundle.json")
        written = op2.flightrecorder.trigger(
            f"chaos_crash_{tag}",
            detail="; ".join(f"[{v.invariant}] {v.message}"
                             for v in violations)[:500],
            force=True, path=path)
        if written:
            self._bundles.append(written)

    def run_crash_site(self, site: str, scenario: int) -> dict:
        """One crashpoint drill: drive incarnation 1 into the armed site,
        let SimulatedCrash tear it off the drive stack, discard its object
        graph, and boot incarnation 2 against the surviving stores."""
        from ..recovery import SimulatedCrash

        plan = FaultPlan.crash(self.seed, site, scenario)
        injector = ChaosInjector(plan)
        clock = FakeClock()
        op, cloud = self._build(clock, name_suffix=f"c{scenario}a")
        op.resilience.use_virtual_sleep()
        workload = self._crash_workload(site, plan)
        errors: "list[str]" = []
        crash = None
        crash_cycle = -1
        ops = [op]
        try:
            injector.tune_operator(op)
            injector.install_crash()
            if site == "deprovisioning.mid_replace":
                # pin to on-demand m.large: spot candidates consolidate by
                # deletion only (capacity-optimized allocation), so a spot
                # node could never stage the replace this drill needs
                op.kube.update("provisioners", "default",
                               self._chaos_provisioner(
                                   ["m.large"],
                                   [wk.CAPACITY_TYPE_ON_DEMAND]))
            # incarnation 1 boots exactly like start(): epoch, then cycles
            op.recovery.begin_incarnation()
            epoch1 = op.recovery.epoch
            self._reconcile_workload(op, workload, injector)
            staged = False
            for cycle in range(self.CRASH_MAX_CYCLES):
                try:
                    staged = staged or self._stage_crash_trigger(
                        op, cloud, site, injector)
                    self._drive_once(op, errors)
                except SimulatedCrash as e:
                    crash, crash_cycle = e, cycle
                    break
                self._reconcile_workload(op, workload, injector)
                clock.step(self.CYCLE_SECONDS)

            # the "process" is dead: faults disarm, the object graph goes
            # away, the kube/cloud/queue stores survive
            injector.enabled = False
            ops.remove(op)
            op.stop()
            op2, _ = self._build(clock, kube=op.kube, cloud=cloud,
                                 queue=getattr(op, "queue", None),
                                 name_suffix=f"c{scenario}b")
            ops.append(op2)
            op2.resilience.use_virtual_sleep()
            injector.tune_operator(op2)
            pending_at_rebirth = [r.name for r in op2.journal.pending()]
            replay, stale_after_budget, settle_cycles = \
                self._recover_and_settle(op2, workload, injector, clock,
                                         errors)
            violations = self._crash_verdict(
                op2, cloud, site, crash, pending_at_rebirth,
                stale_after_budget)
            deduped = (op2.interruption.deduped_count
                       if op2.interruption is not None else 0)
            if site == "interruption.pre_ack" and deduped < 1:
                violations.append(invariants.Violation(
                    "interruption-redelivery-dedupe",
                    "the queue redelivered the unacked message but the "
                    "reborn consumer never deduplicated it"))
            self._crash_bundle(op2, scenario, "invariant_breach", violations)
        finally:
            injector.uninstall_crash()
            for o in ops:
                o.stop()

        return {
            "seed": self.seed,
            "scenario": scenario,
            "drill": f"crash:{site}",
            "site": site,
            "workload_pods": len(workload),
            "plan": plan.describe(),
            "crashed": crash is not None,
            "crash_cycle": crash_cycle,
            "epochs": {"crashed": epoch1, "reborn": op2.recovery.epoch},
            "pending_at_rebirth": pending_at_rebirth,
            "replay": replay,
            "interruption_deduped": deduped,
            "controller_errors": errors,
            "settle_cycles": settle_cycles,
            "final_nodes": len(op2.cluster.nodes),
            "violations": [v.as_dict() for v in violations],
            "passed": not violations,
        }

    def run_crash_failover(self, scenario: int) -> dict:
        """Two-replica drill: the leader crashes mid-launch without
        releasing its lease; the standby takes over through the real
        LeaderElector once the TTL lapses, replays the stranded intent,
        and the store must fence out every late write the zombie
        ex-leader still believes it may make."""
        from ..fake.kube import Fenced
        from ..recovery import SimulatedCrash

        site = "launch.pre_register"
        plan = FaultPlan.crash(self.seed, site, scenario)
        injector = ChaosInjector(plan)
        clock = FakeClock()
        op_a, cloud = self._build(clock, leader_elect=True, identity="op-a",
                                  name_suffix=f"c{scenario}a")
        store = op_a.leader.kube  # the raw store (electors mint epochs on it)
        op_b, _ = self._build(clock, kube=store, cloud=cloud,
                              leader_elect=True, identity="op-b",
                              name_suffix=f"c{scenario}b")
        for o in (op_a, op_b):
            o.resilience.use_virtual_sleep()
            injector.tune_operator(o)
        workload = self._workload(plan)
        errors: "list[str]" = []
        crash = None
        ops = [op_a, op_b]
        try:
            injector.install_crash()
            # manual election ticks (no threads): op-a leads first, and its
            # _on_started_leading callback runs the recovery sequence
            assert op_a.leader.try_acquire_or_renew()
            epoch_a = op_a.leader.fencing_token()
            self._reconcile_workload(op_a, workload, injector)
            for _ in range(self.CRASH_MAX_CYCLES):
                try:
                    self._drive_once(op_a, errors)
                except SimulatedCrash as e:
                    crash = e
                    break
                self._reconcile_workload(op_a, workload, injector)
                clock.step(self.CYCLE_SECONDS)

            injector.enabled = False
            # HARD kill: no release, the lease dangles until the TTL lapses
            clock.step(op_a.leader.lease_duration_s + 1.0)
            assert op_b.leader.try_acquire_or_renew()  # runs recovery hooks
            epoch_b = op_b.leader.fencing_token()
            replay = list(op_b.recovery.replayed)
            pending_after_replay = [r.name for r in op_b.journal.pending(
                before_epoch=op_b.recovery.epoch)]

            # the zombie still believes it leads (its elector never ticked
            # again): every late write must bounce off the fence
            zombie_attempts = 0
            zombie_rejected = 0
            rejected_before = store.fenced_writes_rejected
            with injector.paused():
                for probe in (
                        lambda: op_a.kube.create(
                            "configmaps", "zombie-probe", {"from": "op-a"}),
                        lambda: op_a.kube.delete("pods",
                                                 sorted(workload)[0])):
                    zombie_attempts += 1
                    try:
                        probe()
                    except Fenced:
                        zombie_rejected += 1
            store_rejections = store.fenced_writes_rejected - rejected_before

            # now the zombie's object graph goes away for real (the elector
            # thread never ran, so stop() performs no graceful release —
            # exactly the hard-kill semantics the drill wants)
            ops.remove(op_a)
            op_a.stop()
            settle_cycles = 0
            for _ in range(self.SETTLE_DEADLINE):
                settle_cycles += 1
                self._drive_once(op_b, errors)
                self._reconcile_workload(op_b, workload, injector)
                clock.step(self.CYCLE_SECONDS)
                if self._quiescent(op_b):
                    break

            violations = invariants.check_all(
                op_b, cloud, resilience=op_b.resilience.evidence())
            violations += invariants.check_exactly_once_launch(cloud)
            violations += invariants.check_journal_resolved(op_b)
            violations += invariants.check_fencing(zombie_attempts,
                                                   zombie_rejected)
            if crash is None:
                violations.append(invariants.Violation(
                    "crashpoint-reached",
                    "the leader never reached the armed crashpoint"))
            if not (epoch_a and epoch_b and epoch_b > epoch_a):
                violations.append(invariants.Violation(
                    "fencing-epoch-monotone",
                    f"takeover epoch {epoch_b} is not strictly above the "
                    f"crashed leader's {epoch_a}"))
            if not replay:
                violations.append(invariants.Violation(
                    "journal-write-ahead",
                    "the new leader found nothing to replay after a "
                    "mid-launch leader crash"))
            if pending_after_replay:
                violations.append(invariants.Violation(
                    "journal-replay-budget",
                    f"prior-epoch records {pending_after_replay} survived "
                    "the takeover replay"))
            if not self._quiescent(op_b):
                violations.insert(0, invariants.Violation(
                    "quiescence",
                    "the new leader never reached quiescence before the "
                    "step deadline"))
            self._crash_bundle(op_b, scenario, "failover_breach", violations)
        finally:
            injector.uninstall_crash()
            for o in ops:
                o.stop()

        return {
            "seed": self.seed,
            "scenario": scenario,
            "drill": "crash:leader-failover",
            "site": site,
            "workload_pods": len(workload),
            "plan": plan.describe(),
            "crashed": crash is not None,
            "epochs": {"crashed": epoch_a, "reborn": epoch_b},
            "fence_epoch": store.fence_epoch(),
            "zombie_writes": {"attempted": zombie_attempts,
                              "rejected": zombie_rejected,
                              "store_rejections": store_rejections},
            "replay": replay,
            "controller_errors": errors,
            "settle_cycles": settle_cycles,
            "final_nodes": len(op_b.cluster.nodes),
            "violations": [v.as_dict() for v in violations],
            "passed": not violations,
        }

    def run_crash_drill(self) -> dict:
        from ..recovery import CRASHPOINTS

        t0 = time.time()
        self._bundles = []
        scenarios = [self.run_crash_site(site, i)
                     for i, site in enumerate(CRASHPOINTS)]
        scenarios.append(self.run_crash_failover(len(CRASHPOINTS)))
        artifact = {
            "tool": "karpenter_tpu.chaos",
            "mode": "crash",
            "seed": self.seed,
            "crashpoints": list(CRASHPOINTS),
            "scenario_count": len(scenarios),
            "passed": all(s["passed"] for s in scenarios),
            "scenarios": scenarios,
            # volatile fields below this line only (replay contract)
            "duration_s": round(time.time() - t0, 3),
            "bundles": list(self._bundles),
        }
        if self.out_dir:
            os.makedirs(self.out_dir, exist_ok=True)
            path = os.path.join(self.out_dir,
                                f"chaos_crash_seed{self.seed}.json")
            with open(path, "w") as f:
                json.dump(artifact, f, indent=2, sort_keys=True)
            artifact["artifact_path"] = path
        return artifact

    # -- tenant storm ----------------------------------------------------------

    STORM_TICKS = 48            # armed phase: bursts + steady light traffic
    STORM_DRAIN_DEADLINE = 64   # drain ticks before declaring non-quiescence
    STORM_TENANTS = 8           # 1 hot + 7 light
    STORM_MAX_WAVE = 16
    STORM_BOUND = 4             # starvation bound the invariant asserts

    def run_storm_scenario(self, scenario: int) -> dict:
        """One tenant-storm drill: a hot tenant bursting 16–32 requests
        every 4th tick against 7 light tenants (1 request/tick each),
        all landing in ONE admission bucket of a FleetFrontend whose
        backend is a deterministic stub — the drill measures ADMISSION
        (fairness, shedding, batch composition), not the solver. Offered
        load averages under capacity, so the fairness contract must hold:
        bursts are absorbed without any tenant waiting past the bound.
        Shed probes ride the bursts: one request whose budget cannot
        survive a tick (admission shed) and one whose budget expires
        behind the burst backlog (queue shed). Everything in the returned
        dict is a pure function of (seed, scenario).

        The burst drill doubles as the profiling strict-noop proof: the
        whole storm — fleet ``_dispatch`` gap scopes included — runs with
        the plane disabled and must leave ZERO profiling activity behind
        (invariants.check_profiling_noop). The explain plane runs the
        OPPOSITE way: enabled for the storm, and every shed the fleet
        fires must land in the decision ring citing a SHED_REASONS
        vocabulary entry — the positive half of the provenance
        contract."""
        from .. import explain as _explain
        from .. import profiling as _profiling
        from ..fleet import membership as _membership

        prof_prev = _profiling.set_enabled(False)
        prof_before = _profiling.activity()
        expl_prev = _explain.set_enabled(True)
        expl_before = _explain.activity()
        # the storm never registers replicas, so the membership plane is
        # disabled for the drill and its strict-noop contract is audited
        # on the side (the --partition drill is its positive half)
        mem_prev = _membership.set_enabled(False)
        mem_before = _membership.activity()
        try:
            out = self._storm_scenario_impl(scenario)
            prof_after = _profiling.activity()
            evidence = {"enabled": False, "before": prof_before,
                        "after": prof_after}
            noop = invariants.check_profiling_noop(evidence)
            # store deltas, not absolute counters — replay-deterministic
            out["profiling"] = {
                "enabled": False,
                "deltas": {k: prof_after[k] - prof_before[k]
                           for k in prof_before},
            }
            if noop:
                out["violations"].extend(v.as_dict() for v in noop)
                out["passed"] = False
            mem_after = _membership.activity()
            mem_noop = invariants.check_membership_noop(
                {"enabled": False, "before": mem_before,
                 "after": mem_after})
            out["membership"] = {
                "enabled": False,
                "deltas": {k: mem_after[k] - mem_before[k]
                           for k in mem_before},
            }
            if mem_noop:
                out["violations"].extend(v.as_dict() for v in mem_noop)
                out["passed"] = False
            expl_after = _explain.activity()
            new_sheds = (expl_after["sheds_total"]
                         - expl_before["sheds_total"])
            fired = (out["totals"]["shed_admission"]
                     + out["totals"]["shed_queue"])
            tail = _explain.DECISIONS.records(kind="shed")
            tail = tail[len(tail) - min(new_sheds, len(tail)):]
            reasons: "dict[str, int]" = {}
            uncited = 0
            for rec in tail:
                if rec.get("reason") in _explain.SHED_REASONS and \
                        rec.get("where") in ("admission", "queue"):
                    reasons[rec["reason"]] = reasons.get(rec["reason"], 0) + 1
                else:
                    uncited += 1
            if new_sheds != fired or uncited:
                out["violations"].append(invariants.Violation(
                    "shed-citations",
                    f"storm fired {fired} shed(s) but the decision ring "
                    f"recorded {new_sheds} ({uncited} without a vocabulary "
                    f"reason) — every shed must cite its cause").as_dict())
                out["passed"] = False
            # counts only (never record ids): the ring's monotonic ids
            # depend on process history, and the replay contract says the
            # scenario dict is a pure function of (seed, scenario)
            out["explain"] = {
                "enabled": True,
                "sheds_fired": fired,
                "shed_records": new_sheds,
                "reasons": dict(sorted(reasons.items())),
            }
            return out
        finally:
            _profiling.set_enabled(prof_prev)
            _explain.set_enabled(expl_prev)
            _membership.set_enabled(mem_prev)

    def _storm_scenario_impl(self, scenario: int) -> dict:
        from ..fleet import FleetFrontend

        r = ChaosRng((self.seed << 8) ^ scenario).fork("storm")
        clock = FakeClock()
        mega = []

        def backend(key, problems):
            # deterministic stub: echo per-problem shape so demux order is
            # observable; never touches JAX
            mega.append(len(problems))
            return [{"pods": len(p["pods"])} for p in problems]

        tick_s = 0.02
        fleet = FleetFrontend(solve_batch=backend, clock=clock,
                              tick_interval_s=tick_s,
                              max_wave=self.STORM_MAX_WAVE,
                              starvation_bound=self.STORM_BOUND,
                              name=f"storm-s{scenario}")
        # one shared content key: the fleet's common case — every cluster
        # on the same generated catalog — so all tenants batch together
        key = (0x570124, 0xF1EE7)
        tenants = ["hot"] + [f"t{i}" for i in range(1, self.STORM_TENANTS)]
        for tid in tenants:
            fleet.register_key(tid, key)

        def pods(tid, tick, tag, n=4):
            return [make_pod(f"{tid}-k{tick}-{tag}{i}",
                             cpu="1", memory="2Gi") for i in range(n)]

        bursts = []
        for tick in range(self.STORM_TICKS):
            for tid in tenants[1:]:
                fleet.submit(tid, pods(tid, tick, "l"))
            if tick % 4 == 0:
                burst = r.randint(16, 32)
                bursts.append(burst)
                for i in range(burst):
                    fleet.submit("hot", pods("hot", tick, f"b{i}-"))
                # shed probes: 5ms cannot survive the ~20ms tick -> shed at
                # admission; 45ms survives admission but sits behind the
                # burst (>= 16 ahead in hot's queue, drained ~9/tick) and
                # expires after two ticks -> shed in queue, before compute
                fleet.submit("hot", pods("hot", tick, "pa"), deadline_ms=5)
                fleet.submit("hot", pods("hot", tick, "pq"), deadline_ms=45)
            clock.step(tick_s)
            fleet.tick()

        # drain: no new arrivals, tick until every queue is empty
        drain_ticks = 0
        while fleet.queued() and drain_ticks < self.STORM_DRAIN_DEADLINE:
            drain_ticks += 1
            clock.step(tick_s)
            fleet.tick()

        evidence = fleet.evidence()
        violations = invariants.check_fairness_never_starves(evidence)
        hot = evidence["tenants"]["hot"]
        if hot["shed_admission"] == 0 or hot["shed_queue"] == 0:
            violations.append(invariants.Violation(
                "shed-paths-exercised",
                f"storm fired {hot['shed_admission']} admission shed(s) and "
                f"{hot['shed_queue']} queue shed(s) — both paths must fire "
                f"or the drill proved nothing"))
        totals = {k: sum(st[k] for st in evidence["tenants"].values())
                  for k in ("submitted", "served", "shed_admission",
                            "shed_queue", "errors")}
        # per-tenant shed attribution (tenant x where x reason): the replay
        # artifact names WHO absorbed the shedding, and the invariant
        # reconciles the attribution against the ledger totals
        attribution = fleet.shed_attribution()
        violations.extend(invariants.check_shed_attribution(
            attribution, totals, evidence["tenants"]))
        return {
            "seed": self.seed,
            "scenario": scenario,
            "tenants": len(tenants),
            "storm_ticks": self.STORM_TICKS,
            "drain_ticks": drain_ticks,
            "bursts": bursts,
            "max_wave": self.STORM_MAX_WAVE,
            "starvation_bound": self.STORM_BOUND,
            "mega_solves": len(mega),
            "max_batch": max(mega) if mega else 0,
            "mean_batch": round(sum(mega) / len(mega), 3) if mega else 0.0,
            "totals": totals,
            "shed_attribution": attribution,
            "evidence": evidence,
            "violations": [v.as_dict() for v in violations],
            "passed": not violations,
        }

    def run_storm(self) -> dict:
        t0 = time.time()
        self._bundles = []
        scenarios = [self.run_storm_scenario(s) for s in range(self.scenarios)]
        artifact = {
            "tool": "karpenter_tpu.chaos",
            "mode": "storm",
            "seed": self.seed,
            "tenants": self.STORM_TENANTS,
            "scenario_count": len(scenarios),
            "passed": all(s["passed"] for s in scenarios),
            "scenarios": scenarios,
            # volatile fields below this line only (replay contract)
            "duration_s": round(time.time() - t0, 3),
            "bundles": list(self._bundles),
        }
        if self.out_dir:
            os.makedirs(self.out_dir, exist_ok=True)
            path = os.path.join(self.out_dir,
                                f"chaos_storm_seed{self.seed}.json")
            with open(path, "w") as f:
                json.dump(artifact, f, indent=2, sort_keys=True)
            artifact["artifact_path"] = path
        return artifact

    # -- fleet partition / failover drill --------------------------------------

    PARTITION_REPLICAS = 5
    PARTITION_TENANTS = 40
    PARTITION_WARMUP_TICKS = 12    # > GRAY_MIN_SAMPLES so windows fill
    PARTITION_PHASE_TICKS = 12     # per injected fault
    PARTITION_TIMEOUT_S = 0.25     # caller solve deadline
    PARTITION_HEDGE_S = 0.02       # hedge horizon: ~5x a healthy solve
    GRAY_EJECT_BOUND = 4           # cycles the gray replica may poison p99

    @staticmethod
    def _p99(values: "list[float]") -> float:
        if not values:
            return 0.0
        ordered = sorted(values)
        idx = min(len(ordered) - 1, int(0.99 * len(ordered)))
        return round(ordered[idx], 6)

    def run_partition_scenario(self, scenario: int) -> dict:
        """The failover drill proper, wrapped in the plane switches: the
        membership plane is ON (it is the system under test), the explain
        plane is ON so the poison-quarantine shed lands as a DecisionRecord
        (audited below, storm-style), and profiling stays OFF."""
        from .. import explain as _explain
        from .. import profiling as _profiling
        from ..fleet import membership as _membership

        prof_prev = _profiling.set_enabled(False)
        expl_prev = _explain.set_enabled(True)
        mem_prev = _membership.set_enabled(True)
        expl_before = _explain.activity()
        try:
            out = self._partition_scenario_impl(scenario)
            expl_after = _explain.activity()
            new_sheds = (expl_after["sheds_total"]
                         - expl_before["sheds_total"])
            fired = out["totals"]["shed_quarantine"]
            tail = _explain.DECISIONS.records(kind="shed")
            tail = tail[len(tail) - min(new_sheds, len(tail)):]
            uncited = sum(
                1 for rec in tail
                if rec.get("reason") not in _explain.SHED_REASONS
                or rec.get("where") != "failover")
            if new_sheds != fired or uncited:
                out["violations"].append(invariants.Violation(
                    "shed-citations",
                    f"drill fired {fired} quarantine shed(s) but the "
                    f"decision ring recorded {new_sheds} ({uncited} without "
                    f"a failover vocabulary reason) — every shed must cite "
                    f"its cause").as_dict())
                out["passed"] = False
            out["explain"] = {
                "enabled": True,
                "sheds_fired": fired,
                "shed_records": new_sheds,
            }
            return out
        finally:
            _membership.set_enabled(mem_prev)
            _explain.set_enabled(expl_prev)
            _profiling.set_enabled(prof_prev)

    def _partition_scenario_impl(self, scenario: int) -> dict:
        from ..fleet import (FailoverClient, FailoverExhausted, FleetRouter,
                             MembershipManager, QuarantineRing,
                             ReplicaUnavailable, RequestQuarantined)
        from ..resilience import RetryBudget

        r = ChaosRng((self.seed << 8) ^ scenario).fork("partition")
        clock = FakeClock()
        names = [f"replica-{i}" for i in range(self.PARTITION_REPLICAS)]
        faults = {"unavailable": 0, "timeout": 0, "crash": 0}
        stubs = {n: _StubReplica(n, round(0.002 + r.uniform() * 0.002, 6),
                                 clock)
                 for n in names}
        # the three single-fault victims, distinct by construction
        kill_n, part_n, gray_n = (
            names[i] for i in r.sample_indices(3, len(names)))

        router = FleetRouter()
        ejection_triggers: "list[str]" = []
        manager = MembershipManager(
            router, clock=clock,
            flight_trigger=lambda reason, detail:
                ejection_triggers.append(reason))
        for n in names:
            manager.register(n, stubs[n].probe)

        def make_transport(stub):
            def transport(tenant_id, request, timeout_s):
                try:
                    return stub.solve(tenant_id, request, timeout_s)
                except ReplicaUnavailable as e:
                    faults[e.fault_kind] += 1
                    raise
            return transport

        resyncs = {"n": 0}

        def on_remap(tenant_id, replica):
            # cold-start handling: re-Sync the tenant's catalog onto its
            # new home before the solve is handed over
            resyncs["n"] += 1
            stubs[replica].synced.add(tenant_id)

        client = FailoverClient(
            router, {n: make_transport(stubs[n]) for n in names},
            clock=clock, quarantine=QuarantineRing(), on_remap=on_remap,
            seed=self.seed, hedge_horizon_s=self.PARTITION_HEDGE_S,
            budget=RetryBudget(capacity=64.0, refill_per_success=0.5))

        tenants = [f"tenant-{i:02d}" for i in range(self.PARTITION_TENANTS)]
        poison_req = {"poison": True, "tenant": "tenant-toxic", "pods": 4}

        epochs = [manager.epoch()]
        outcomes: "list[dict]" = []
        tick_no = {"n": 0}

        def one_cycle(phase_events, cyc_lats, greens, poison=False):
            tick_no["n"] += 1
            phase_events.extend(manager.tick())
            epochs.append(manager.epoch())
            f0 = sum(faults.values())
            lats: "list[float]" = []
            if router.replicas:
                todo = [(t, {"tenant": t, "cycle": tick_no["n"], "pods": 4})
                        for t in tenants]
                if poison:
                    todo.append(("tenant-toxic", poison_req))
                for t, req in todo:
                    t0 = clock.now()
                    try:
                        client.solve(t, req,
                                     timeout_s=self.PARTITION_TIMEOUT_S)
                    except RequestQuarantined:
                        outcomes.append({"tenant": t, "outcome": "shed",
                                         "reason": "poison-quarantine"})
                    except (FailoverExhausted, LookupError) as e:
                        outcomes.append({
                            "tenant": t, "outcome": "error",
                            "detail": f"{type(e).__name__}: {e}"})
                    else:
                        outcomes.append({"tenant": t, "outcome": "served"})
                        lats.append(round(clock.now() - t0, 6))
            cyc_lats.append(self._p99(lats))
            greens.append(sum(faults.values()) == f0)
            clock.step(1.0)  # heartbeat cadence
            return lats

        def run_phase(name, ticks, poison=False):
            events: "list[dict]" = []
            p99s: "list[float]" = []
            greens: "list[bool]" = []
            all_lats: "list[float]" = []
            for _ in range(ticks):
                all_lats.extend(one_cycle(events, p99s, greens,
                                          poison=poison))
            green_at = next((i + 1 for i, g in enumerate(greens) if g), -1)
            return {"phase": name, "ticks": ticks, "events": events,
                    "cycle_p99": p99s, "p99": self._p99(all_lats),
                    "recovery_to_green_cycles": green_at}

        violations: "list[invariants.Violation]" = []
        phases = [run_phase("warmup", self.PARTITION_WARMUP_TICKS)]
        baseline_p99 = phases[0]["p99"]
        a0 = router.assignment(tenants)

        # phase 2: hard kill — K missed beats must eject, the client must
        # reroute the dead replica's tenants, nobody else may move
        stubs[kill_n].state = "dead"
        phases.append(run_phase("kill", self.PARTITION_PHASE_TICKS))
        a_kill = router.assignment(tenants)
        violations += invariants.check_remap_blast_radius(
            a0, a_kill, {kill_n})
        remapped = sum(1 for t in tenants if a0[t] != a_kill[t])
        remap_fraction = round(remapped / float(len(tenants)), 4)

        # phase 3: blackhole partition — probes and requests time out
        # instead of failing fast; same detector, the hedge covers clients
        stubs[kill_n].state = "ok"
        stubs[part_n].state = "partitioned"
        phases.append(run_phase("partition", self.PARTITION_PHASE_TICKS))

        # phase 4: gray failure — the replica still answers, slowly; the
        # latency-quantile detector must eject it before fleet p99 stays
        # doubled (the hedge bounds the damage while detection runs)
        stubs[part_n].state = "ok"
        stubs[gray_n].state = "slow"
        phases.append(run_phase("gray", self.PARTITION_PHASE_TICKS))
        gray = phases[-1]
        gray_ejections = [e for e in gray["events"]
                          if e.get("reason") == "gray-failure"]
        elevated = sum(1 for p in gray["cycle_p99"]
                       if p >= 2.0 * baseline_p99)
        if not gray_ejections:
            violations.append(invariants.Violation(
                "gray-ejection-before-p99-doubles",
                f"the slow replica {gray_n} was never ejected by the "
                "latency-quantile detector"))
        elif elevated > self.GRAY_EJECT_BOUND \
                or gray["cycle_p99"][-1] >= 2.0 * baseline_p99:
            violations.append(invariants.Violation(
                "gray-ejection-before-p99-doubles",
                f"fleet p99 stayed >= 2x baseline ({baseline_p99}s) for "
                f"{elevated} gray-phase cycle(s) (bound "
                f"{self.GRAY_EJECT_BOUND}), last cycle "
                f"{gray['cycle_p99'][-1]}s — ejection came too late"))

        # phase 5: poison pill — one request crashes whatever replica
        # serves it; after exactly VICTIM_LIMIT distinct victims it must be
        # quarantined and shed, never handed a third replica
        stubs[gray_n].state = "ok"
        phases.append(run_phase("poison", self.PARTITION_PHASE_TICKS,
                                poison=True))
        q_evidence = client.quarantine.evidence()
        violations += invariants.check_quarantine_cascade(
            q_evidence["victims"], limit=client.quarantine.victim_limit)
        from ..fleet.failover import request_fingerprint
        poison_fp = request_fingerprint(poison_req)
        poison_victims = client.quarantine.victims(poison_fp)
        if len(poison_victims) != client.quarantine.victim_limit:
            violations.append(invariants.Violation(
                "quarantine-bounds-cascade",
                f"the poison request claimed {len(poison_victims)} "
                f"victim(s) {poison_victims} — the drill expects exactly "
                f"{client.quarantine.victim_limit} before quarantine"))

        # phase 6: rejoin — every faulted replica heals, recovers through
        # the probe gate, and the rendezvous assignment must come back
        # bit-identical to the pre-fault baseline
        for stub in stubs.values():
            stub.state = "ok"
        phases.append(run_phase("rejoin", self.PARTITION_PHASE_TICKS))
        a_final = router.assignment(tenants)
        violations += invariants.check_remap_blast_radius(
            a0, a_final, set())

        violations += invariants.check_completes_or_sheds(outcomes)
        violations += invariants.check_epoch_monotone(epochs)
        ejections = [e for p in phases for e in p["events"]
                     if e["event"] == "ReplicaEjected"]
        if len(ejection_triggers) != len(ejections):
            violations.append(invariants.Violation(
                "membership-epoch-monotone",
                f"{len(ejections)} ejection(s) fired but "
                f"{len(ejection_triggers)} flight-recorder trigger(s) were "
                "pulled — the forensics edge is miswired"))

        outcome_counts = {"served": 0, "shed": 0, "error": 0}
        for o in outcomes:
            outcome_counts[o["outcome"]] += 1
        totals = {
            "solves": len(outcomes),
            "served": outcome_counts["served"],
            "shed_quarantine": outcome_counts["shed"],
            "errors": outcome_counts["error"],
            "faults": dict(faults),
            "cold_remaps": client.warm_state_losses,
            "resyncs": resyncs["n"],
        }
        return {
            "seed": self.seed,
            "scenario": scenario,
            "drill": "partition",
            "replicas": len(names),
            "tenants": len(tenants),
            "faulted": {"killed": kill_n, "partitioned": part_n,
                        "gray": gray_n},
            "baseline_p99_s": baseline_p99,
            "remap_fraction": remap_fraction,
            "remap_expected": round(1.0 / len(names), 4),
            "recovery_to_green_cycles": {
                p["phase"]: p["recovery_to_green_cycles"]
                for p in phases[1:]},
            "gray_elevated_cycles": elevated,
            "membership_epoch": manager.epoch(),
            "epoch_observations": len(epochs),
            "ejection_flight_triggers": len(ejection_triggers),
            "phases": phases,
            "totals": totals,
            "quarantine": q_evidence,
            "failover": client.evidence(),
            "membership": manager.snapshot(),
            "violations": [v.as_dict() for v in violations],
            "passed": not violations,
        }

    def run_partition_noop(self, scenario: int) -> dict:
        """The strict-noop half: with the membership plane disabled, a
        replica death must change NOTHING — register() and tick() are
        inert, routing stays bit-identical to the static member set, and
        the plane's activity counters stay frozen."""
        from ..fleet import FleetRouter, MembershipManager
        from ..fleet import membership as _membership

        names = [f"replica-{i}" for i in range(self.PARTITION_REPLICAS)]
        tenants = [f"tenant-{i:02d}" for i in range(self.PARTITION_TENANTS)]
        router = FleetRouter(names)
        a0 = router.assignment(tenants)

        def dead_probe():
            raise RuntimeError("replica is down")

        prev = _membership.set_enabled(False)
        before = _membership.activity()
        try:
            clock = FakeClock()
            manager = MembershipManager(router, clock=clock)
            for n in names:
                manager.register(n, dead_probe)
            events: "list[dict]" = []
            for _ in range(2 * MembershipManager.MISSED_BEATS_K):
                events.extend(manager.tick())
                clock.step(1.0)
            after = _membership.activity()
        finally:
            _membership.set_enabled(prev)

        evidence = {"enabled": False, "before": before, "after": after}
        violations = invariants.check_membership_noop(evidence)
        a1 = router.assignment(tenants)
        moved = sorted(t for t in tenants if a0[t] != a1[t])
        if moved or tuple(router.replicas) != tuple(names):
            violations.append(invariants.Violation(
                "membership-strict-noop",
                f"routing moved with the plane disabled: {len(moved)} "
                f"tenant(s) remapped, members {list(router.replicas)}"))
        if events:
            violations.append(invariants.Violation(
                "membership-strict-noop",
                f"tick() returned {len(events)} event(s) while disabled"))
        return {
            "seed": self.seed,
            "scenario": scenario,
            "drill": "partition-noop",
            "replicas": len(names),
            "tenants": len(tenants),
            "membership": {
                "enabled": False,
                "deltas": {k: after[k] - before[k] for k in before},
            },
            "epoch": manager.epoch(),
            "violations": [v.as_dict() for v in violations],
            "passed": not violations,
        }

    def run_partition_drill(self) -> dict:
        t0 = time.time()
        self._bundles = []
        scenarios = [self.run_partition_scenario(0),
                     self.run_partition_noop(1)]
        drill = scenarios[0]
        artifact = {
            "tool": "karpenter_tpu.chaos",
            "mode": "partition",
            "seed": self.seed,
            "replicas": self.PARTITION_REPLICAS,
            "tenants": self.PARTITION_TENANTS,
            "scenario_count": len(scenarios),
            "passed": all(s["passed"] for s in scenarios),
            "key_numbers": {
                "remap_fraction": drill["remap_fraction"],
                "remap_expected": drill["remap_expected"],
                "recovery_to_green_cycles": max(
                    drill["recovery_to_green_cycles"].values()),
                "warm_state_losses": drill["totals"]["cold_remaps"],
                "gray_elevated_cycles": drill["gray_elevated_cycles"],
                "poisons_quarantined": len(
                    drill["quarantine"]["quarantined"]),
            },
            "scenarios": scenarios,
            # volatile fields below this line only (replay contract)
            "duration_s": round(time.time() - t0, 3),
            "bundles": list(self._bundles),
        }
        if self.out_dir:
            os.makedirs(self.out_dir, exist_ok=True)
            path = os.path.join(self.out_dir,
                                f"failover_seed{self.seed}.json")
            with open(path, "w") as f:
                json.dump(artifact, f, indent=2, sort_keys=True)
            artifact["artifact_path"] = path
        return artifact

    # -- spot reclaim-storm drill ----------------------------------------------

    SPOT_STORM_NODES = 10_000     # fleet size for the headline drill
    SPOT_STORM_RECLAIMS = 2_000   # simultaneous reclaim warnings, ONE tick
    SPOT_RESTORE_K = 5            # cycles granted to rebind every displaced pod
    SPOT_PRESTORM_CYCLES = 4      # proactive-rebalance window before the burst
    SPOT_SEED_DEADLINE = 12       # cycles granted for the fleet to initialize
    SPOT_WRONG_NODES = 90         # forecaster-was-wrong fleet
    SPOT_WRONG_RECLAIMS = 12
    SPOT_NOOP_CYCLES = 6          # decision-parity window, strict-noop half
    SPOT_OD_EVERY = 10            # every Nth seeded node is on-demand

    def _seed_spot_fleet(self, op, n_nodes: int) -> "dict[str, dict]":
        """Bulk-bootstrap a large, mostly-spot t.small fleet: every node
        carries one full-node pod (cpu fills the allocatable, so displaced
        pods can never double-stack onto survivors — restoring capacity
        means launching real replacements). Round-robin zones, every
        SPOT_OD_EVERY-th node on-demand. Nodes go through the REAL launch
        path (_launch_node: journal write-ahead, machine object, cloud
        instance, lifecycle hydration) so the reclaim storm exercises the
        same machinery production would."""
        from ..oracle.scheduler import Option
        from ..solver.core import SolvedNode, SolveResult

        catalog = op.cloudprovider.catalog_for(None)
        itype = catalog.by_name["t.small"]
        prov = op.kube.get("provisioners", "default")
        empty = SolveResult(nodes=[], existing_counts={}, unschedulable={},
                            groups=[])
        price_of = {(o.zone, o.capacity_type): o.price
                    for o in itype.offerings}
        zones = sorted({o.zone for o in itype.offerings})
        fleet: "dict[str, dict]" = {}
        for i in range(n_nodes):
            zone = zones[i % len(zones)]
            ct = (wk.CAPACITY_TYPE_ON_DEMAND
                  if i % self.SPOT_OD_EVERY == self.SPOT_OD_EVERY - 1
                  else wk.CAPACITY_TYPE_SPOT)
            solved = SolvedNode(
                option=Option(index=-1, itype=itype, zone=zone,
                              capacity_type=ct, price=price_of[(zone, ct)],
                              alloc=tuple(itype.allocatable_vector())),
                pod_counts={}, provisioner=prov)
            node = op.provisioning._launch_node(solved, {}, empty)
            if node is None:
                continue
            pod_name = f"sp-{i:05d}"
            shape = {"cpu": "2", "memory": "1Gi"}
            op.kube.create("pods", pod_name, make_pod(pod_name, **shape))
            op.provisioning._bind_assigned({0: [pod_name]}, node.name)
            fleet[pod_name] = shape
        return fleet

    def _storm_replicaset(self, op, fleet: "dict[str, dict]") -> None:
        """ReplicaSet analogue for the storm fleet: pods whose node was
        reclaimed come back as fresh unbound pods (same contract as
        _reconcile_workload, without an injector in the loop)."""
        for name, shape in fleet.items():
            obj = op.kube.get("pods", name)
            if obj is not None and obj.node_name \
                    and obj.node_name not in op.cluster.nodes:
                op.kube.delete("pods", name)
                obj = None
            if obj is None:
                op.kube.create("pods", name, make_pod(name, **shape))

    def _drain_interruption_queue(self, op) -> int:
        """Deliver EVERY queued reclaim warning inside the current tick:
        the interruption controller receives in batches of 10, so one
        reconcile per cycle would smear a 2000-message storm across 200
        cycles — a storm is simultaneous by definition."""
        drained = 0
        while True:
            n = op.interruption.reconcile_once()
            if n == 0:
                return drained
            drained += n

    @staticmethod
    def _fleet_cost(op) -> float:
        return round(sum(n.price for n in op.cluster.nodes.values()), 4)

    def _pool_nodes(self, op, pool) -> "list":
        return [n for n in op.cluster.nodes.values()
                if (n.instance_type, n.zone, n.capacity_type) == pool]

    def run_spot_storm_scenario(self, scenario: int, n_nodes: int,
                                n_reclaims: int) -> dict:
        """The headline drill: forecast the storm, rebalance ahead of it,
        then reclaim n_reclaims spot nodes in one tick and audit the
        recovery. Explain is ON (risk-term DecisionRecords are part of
        the contract), profiling OFF, the spot plane hot."""
        from .. import explain as _explain
        from .. import profiling as _profiling
        from .. import spot as spot_plane

        prof_prev = _profiling.set_enabled(False)
        expl_prev = _explain.set_enabled(True)
        spot_prev = spot_plane.set_enabled(True)
        rng = ChaosRng((self.seed << 8) ^ scenario).fork("spotstorm")
        clock = FakeClock()
        op, cloud = self._build(clock, name_suffix=f"ss{scenario}")
        op.resilience.use_virtual_sleep()
        shrink_batcher_windows(op)
        # consolidation would spend the whole drill bin-packing the huge
        # fleet; the storm is about the interruption/rebalance planes
        op.kube.update("provisioners", "default",
                       self._chaos_provisioner(consolidation=False))
        errors: "list[str]" = []
        violations: "list[invariants.Violation]" = []
        storm_pool = ("t.small", "zone-1a", wk.CAPACITY_TYPE_SPOT)
        try:
            fleet = self._seed_spot_fleet(op, n_nodes)
            seed_cycles = 0
            for _ in range(self.SPOT_SEED_DEADLINE):
                seed_cycles += 1
                self._drive_once(op, errors)
                clock.step(self.CYCLE_SECONDS)
                if self._quiescent(op):
                    break
            pre_cost = self._fleet_cost(op)
            pre_nodes = len(op.cluster.nodes)
            # phase A — the forecaster sees the storm coming: live feed
            # pins the stormed pool at rate 0.9, the rebalance controller
            # starts draining ahead of it (rate-limited, cost-guarded)
            schedule = {storm_pool: 0.9}
            op.spotforecaster.set_live_source(lambda: dict(schedule))
            for _ in range(self.SPOT_PRESTORM_CYCLES):
                self._drive_once(op, errors)
                self._storm_replicaset(op, fleet)
                clock.step(self.CYCLE_SECONDS)
            prestorm_rebalances = len(op.spotrebalance.ledger)
            # phase B — the storm tick: the forecaster was RIGHT, and the
            # platform reclaims n_reclaims instances of the stormed pool
            # simultaneously. Every warning is delivered inside this tick.
            pool_iids = sorted(
                i.id for i in cloud.instances.values()
                if i.state == "running"
                and (i.instance_type, i.zone, i.capacity_type) == storm_pool)
            picks = rng.sample_indices(min(n_reclaims, len(pool_iids)),
                                       len(pool_iids))
            targets = [pool_iids[i] for i in sorted(picks)]
            machines_before_storm = {m.name for m in op.kube.machines()}
            for iid in targets:
                op.queue.send(json.dumps({
                    "source": "cloud.spot",
                    "detail-type": "Spot Instance Interruption Warning",
                    "detail": {"instance-id": iid}}))
            delivered = self._drain_interruption_queue(op)
            self._drive_once(op, errors)
            self._storm_replicaset(op, fleet)
            clock.step(self.CYCLE_SECONDS)
            # phase C — restore: every displaced pod must be bound again
            # within SPOT_RESTORE_K cycles
            restore_cycles = -1
            for c in range(1, 2 * self.SPOT_RESTORE_K + 1):
                self._drive_once(op, errors)
                self._storm_replicaset(op, fleet)
                if not op.kube.pending_pods():
                    restore_cycles = c
                    clock.step(self.CYCLE_SECONDS)
                    break
                clock.step(self.CYCLE_SECONDS)
            # composition audit evidence BEFORE the GC time-jumps expire
            # the ICE marks: no post-storm launch may land in the stormed
            # (quarantined) pool while the forecast still brands it
            post_storm_in_pool = [
                n.name for n in self._pool_nodes(op, storm_pool)
                if n.machine_name not in machines_before_storm]
            pool_iced = not any(
                o.available and o.zone == storm_pool[1]
                and o.capacity_type == storm_pool[2]
                for o in op.cloudprovider.catalog_for(None)
                .by_name[storm_pool[0]].offerings)
            risk_records = [r for r in _explain.DECISIONS.records(
                kind="spot-objective") if r.get("forecast_rung") == 0]
            # the storm has happened: the live feed stops branding the
            # pool (ICE keeps quarantining it) — otherwise the rebalance
            # controller would churn zone-1a survivors through the whole
            # settle phase and the fleet could never quiesce
            op.spotforecaster.set_live_source(lambda: {})
            # settle + GC mop-up (clears the reclaimed machine objects)
            settle_cycles = 0
            for _ in range(self.SETTLE_DEADLINE):
                settle_cycles += 1
                self._drive_once(op, errors)
                self._storm_replicaset(op, fleet)
                clock.step(self.CYCLE_SECONDS)
                if self._quiescent(op):
                    break
            for _ in range(2):
                clock.step(360.0)
                self._drive_once(op, errors)
            for _ in range(6):
                self._drive_once(op, errors)
                self._storm_replicaset(op, fleet)
                clock.step(self.CYCLE_SECONDS)
                if self._quiescent(op):
                    break
            post_cost = self._fleet_cost(op)
            spot_after = spot_plane.activity()

            violations += invariants.check_all(
                op, cloud, resilience=op.resilience.evidence())
            violations += invariants.check_spot_cost_never_raised(
                op.spotrebalance.ledger)
            violations += invariants.check_spot_capacity_restored(
                restore_cycles, self.SPOT_RESTORE_K)
            violations += invariants.check_spot_never_strands(
                op, op.spotrebalance.ledger)
            if delivered < n_reclaims:
                violations.append(invariants.Violation(
                    "spot-storm-delivery",
                    f"only {delivered} of {n_reclaims} reclaim warnings "
                    "were delivered in the storm tick"))
            if post_storm_in_pool:
                violations.append(invariants.Violation(
                    "spot-quarantine-composition",
                    f"{len(post_storm_in_pool)} post-storm launch(es) "
                    f"landed in the stormed pool {list(storm_pool)} while "
                    f"it was ICE-quarantined: {post_storm_in_pool[:5]}"))
            if not pool_iced:
                violations.append(invariants.Violation(
                    "spot-quarantine-composition",
                    f"the stormed pool {list(storm_pool)} was never "
                    "ICE-marked by the interruption handler"))
            if not risk_records:
                violations.append(invariants.Violation(
                    "spot-risk-citations",
                    "no spot-objective DecisionRecord cites the live "
                    "forecast (rung 0) — risk-influenced assignments "
                    "must carry their risk term"))
            lim = op.spotrebalance.limiter.snapshot()
            if lim["spent"] > lim["accrued"] + 1e-9:
                violations.append(invariants.Violation(
                    "spot-churn-le-risk-avoided",
                    f"rebalance spent {lim['spent']} drain token(s) but "
                    f"only {lim['accrued']} of predicted-interruption "
                    "mass ever accrued"))
            if not self._quiescent(op):
                violations.insert(0, invariants.Violation(
                    "quiescence",
                    "storm fleet never reached quiescence before the "
                    "step deadline"))
            if violations and self.out_dir:
                os.makedirs(self.out_dir, exist_ok=True)
                bundle_path = os.path.join(
                    self.out_dir,
                    f"spotstorm_seed{self.seed}_s{scenario}_bundle.json")
                written = op.flightrecorder.trigger(
                    "spot_storm_invariant_breach",
                    detail="; ".join(f"[{v.invariant}] {v.message}"
                                     for v in violations)[:500],
                    force=True, path=bundle_path)
                if written:
                    self._bundles.append(written)
        finally:
            spot_plane.set_enabled(spot_prev)
            _explain.set_enabled(expl_prev)
            _profiling.set_enabled(prof_prev)
            op.stop()

        reb = op.spotrebalance
        return {
            "seed": self.seed,
            "scenario": scenario,
            "drill": "spot-storm",
            "fleet": {
                "nodes": pre_nodes,
                "seed_cycles": seed_cycles,
                "pods": len(fleet),
                "stormed_pool": list(storm_pool),
                "stormed_pool_size": len(pool_iids),
                "hourly_cost_before": pre_cost,
            },
            "storm": {
                "reclaims_sent": len(targets),
                "reclaims_delivered": delivered,
                "restore_cycles": restore_cycles,
                "restore_bound": self.SPOT_RESTORE_K,
            },
            "rebalance": {
                "prestorm_proactive": prestorm_rebalances,
                "ledger": [dict(e) for e in reb.ledger],
                "limiter": lim,
                "snapshot": reb.snapshot(),
            },
            "composition": {
                "stormed_pool_iced": pool_iced,
                "post_storm_launches_into_stormed_pool":
                    len(post_storm_in_pool),
                "risk_decision_records": len(risk_records),
            },
            "forecast": op.spotforecaster.snapshot(),
            "spot_activity": {k: v for k, v in sorted(spot_after.items())},
            "hourly_cost_after": post_cost,
            "controller_errors": errors,
            "settle_cycles": settle_cycles,
            "final_nodes": len(op.cluster.nodes),
            "violations": [v.as_dict() for v in violations],
            "passed": not violations,
        }

    def run_spot_wrong_forecast(self, scenario: int) -> dict:
        """The adversarial half: the forecaster predicts a storm on pool
        B, the platform reclaims pool A. The drill audits that being
        WRONG costs bounded churn — proactive drains never exceed the
        accrued predicted-interruption mass, clearing the forecast stops
        rebalancing within one reconcile, recovery still lands within the
        restore bound, and no replacement ever raised the bill."""
        from .. import explain as _explain
        from .. import profiling as _profiling
        from .. import spot as spot_plane

        prof_prev = _profiling.set_enabled(False)
        expl_prev = _explain.set_enabled(True)
        spot_prev = spot_plane.set_enabled(True)
        rng = ChaosRng((self.seed << 8) ^ scenario).fork("spotwrong")
        clock = FakeClock()
        op, cloud = self._build(clock, name_suffix=f"sw{scenario}")
        op.resilience.use_virtual_sleep()
        shrink_batcher_windows(op)
        op.kube.update("provisioners", "default",
                       self._chaos_provisioner(consolidation=False))
        errors: "list[str]" = []
        violations: "list[invariants.Violation]" = []
        forecast_pool = ("t.small", "zone-1b", wk.CAPACITY_TYPE_SPOT)
        actual_pool = ("t.small", "zone-1a", wk.CAPACITY_TYPE_SPOT)
        try:
            fleet = self._seed_spot_fleet(op, self.SPOT_WRONG_NODES)
            for _ in range(self.SPOT_SEED_DEADLINE):
                self._drive_once(op, errors)
                clock.step(self.CYCLE_SECONDS)
                if self._quiescent(op):
                    break
            schedule = {forecast_pool: 0.9}
            op.spotforecaster.set_live_source(lambda: dict(schedule))
            for _ in range(self.SPOT_PRESTORM_CYCLES):
                self._drive_once(op, errors)
                self._storm_replicaset(op, fleet)
                clock.step(self.CYCLE_SECONDS)
            # the storm lands where the forecast did NOT point
            pool_iids = sorted(
                i.id for i in cloud.instances.values()
                if i.state == "running"
                and (i.instance_type, i.zone, i.capacity_type)
                == actual_pool)
            picks = rng.sample_indices(
                min(self.SPOT_WRONG_RECLAIMS, len(pool_iids)),
                len(pool_iids))
            for idx in sorted(picks):
                op.queue.send(json.dumps({
                    "source": "cloud.spot",
                    "detail-type": "Spot Instance Interruption Warning",
                    "detail": {"instance-id": pool_iids[idx]}}))
            delivered = self._drain_interruption_queue(op)
            self._drive_once(op, errors)
            self._storm_replicaset(op, fleet)
            clock.step(self.CYCLE_SECONDS)
            restore_cycles = -1
            for c in range(1, 2 * self.SPOT_RESTORE_K + 1):
                self._drive_once(op, errors)
                self._storm_replicaset(op, fleet)
                if not op.kube.pending_pods():
                    restore_cycles = c
                    clock.step(self.CYCLE_SECONDS)
                    break
                clock.step(self.CYCLE_SECONDS)
            # the operator admits the forecast was wrong: the live feed
            # clears, and proactive churn must STOP within one reconcile
            # (the limiter zeroes its bank on the first zero-mass cycle)
            op.spotforecaster.set_live_source(lambda: {})
            launched_at_clear = spot_plane.activity()[
                "spot_rebalance_launched"]
            post_clear_cycles = 3
            for _ in range(post_clear_cycles):
                self._drive_once(op, errors)
                self._storm_replicaset(op, fleet)
                clock.step(self.CYCLE_SECONDS)
            launched_after = spot_plane.activity()["spot_rebalance_launched"]
            settle_cycles = 0
            for _ in range(self.SETTLE_DEADLINE):
                settle_cycles += 1
                self._drive_once(op, errors)
                self._storm_replicaset(op, fleet)
                clock.step(self.CYCLE_SECONDS)
                if self._quiescent(op):
                    break
            for _ in range(2):
                clock.step(360.0)
                self._drive_once(op, errors)
            for _ in range(6):
                self._drive_once(op, errors)
                self._storm_replicaset(op, fleet)
                clock.step(self.CYCLE_SECONDS)
                if self._quiescent(op):
                    break

            violations += invariants.check_all(
                op, cloud, resilience=op.resilience.evidence())
            violations += invariants.check_spot_cost_never_raised(
                op.spotrebalance.ledger)
            violations += invariants.check_spot_capacity_restored(
                restore_cycles, self.SPOT_RESTORE_K)
            violations += invariants.check_spot_never_strands(
                op, op.spotrebalance.ledger)
            lim = op.spotrebalance.limiter.snapshot()
            if lim["spent"] > lim["accrued"] + 1e-9:
                violations.append(invariants.Violation(
                    "spot-churn-le-risk-avoided",
                    f"a WRONG forecast let rebalance spend {lim['spent']} "
                    f"drain(s) against {lim['accrued']} accrued mass"))
            if launched_after > launched_at_clear:
                violations.append(invariants.Violation(
                    "spot-churn-le-risk-avoided",
                    f"{launched_after - launched_at_clear} proactive "
                    f"launch(es) fired in the {post_clear_cycles} cycles "
                    "AFTER the forecast cleared — a wrong forecaster must "
                    "stop causing churn within one reconcile"))
            if not self._quiescent(op):
                violations.insert(0, invariants.Violation(
                    "quiescence",
                    "wrong-forecast fleet never reached quiescence"))
        finally:
            spot_plane.set_enabled(spot_prev)
            _explain.set_enabled(expl_prev)
            _profiling.set_enabled(prof_prev)
            op.stop()

        return {
            "seed": self.seed,
            "scenario": scenario,
            "drill": "spot-wrong-forecast",
            "fleet_nodes": self.SPOT_WRONG_NODES,
            "forecast_pool": list(forecast_pool),
            "actual_pool": list(actual_pool),
            "reclaims_delivered": delivered,
            "restore_cycles": restore_cycles,
            "proactive_rebalances": len(op.spotrebalance.ledger),
            "rebalance_ledger": [dict(e) for e in op.spotrebalance.ledger],
            "limiter": lim,
            "post_clear_launches": launched_after - launched_at_clear,
            "controller_errors": errors,
            "settle_cycles": settle_cycles,
            "final_nodes": len(op.cluster.nodes),
            "violations": [v.as_dict() for v in violations],
            "passed": not violations,
        }

    def _spot_noop_window(self, live_schedule) -> "tuple[dict, dict]":
        """One decision-parity window: a fresh operator with a pinned
        machine-name suffix, a fixed workload, SPOT_NOOP_CYCLES drives.
        Returns (decisions, controller-error list). The caller flips the
        spot plane around this; `live_schedule` is injected regardless —
        the SWITCH, not the feed, must gate the plane."""
        clock = FakeClock()
        op, _cloud = self._build(clock, name_suffix="ssnoop")
        op.resilience.use_virtual_sleep()
        shrink_batcher_windows(op)
        op.kube.update("provisioners", "default",
                       self._chaos_provisioner(consolidation=False))
        op.spotforecaster.set_live_source(lambda: dict(live_schedule))
        workload = {f"np{i}": {"cpu": c, "memory": m}
                    for i, (c, m) in enumerate(
                        [("1", "2Gi"), ("2", "4Gi"), ("500m", "1Gi")] * 4)}
        errors: "list[str]" = []
        try:
            for name, shape in workload.items():
                op.kube.create("pods", name, make_pod(name, **shape))
            for _ in range(self.SPOT_NOOP_CYCLES):
                self._drive_once(op, errors)
                clock.step(self.CYCLE_SECONDS)
        finally:
            op.stop()
        machines = sorted(
            (m.name, m.status.instance_type, m.status.zone,
             m.status.capacity_type)
            for m in op.kube.machines())
        bindings = {p.name: p.node_name
                    for p in (op.kube.get("pods", n) for n in workload)
                    if p is not None}
        decisions = {
            "machines": [list(m) for m in machines],
            "bindings": dict(sorted(bindings.items())),
            "nodes": sorted(
                (n.name, n.instance_type, n.zone, n.capacity_type,
                 round(n.price, 6))
                for n in op.cluster.nodes.values()),
        }
        return decisions, {"errors": errors}

    def run_spot_noop(self, scenario: int) -> dict:
        """The strict-noop half, two windows: window A runs with the
        plane ENABLED, window B DISABLED — both get the same hot live
        schedule injected. Disabled must mean disabled: zero counter
        movement AND launch/bind decisions bit-identical to... nothing,
        because window A's forecast steers its solve. So window A runs
        WITHOUT an elevated schedule (the advisory plane at its quiet
        default — the no-plane baseline by construction) and window B
        runs DISABLED with the hot schedule: if the switch leaks, B's
        decisions drift from A's baseline or B's counters move."""
        from .. import explain as _explain
        from .. import profiling as _profiling
        from .. import spot as spot_plane

        prof_prev = _profiling.set_enabled(False)
        expl_prev = _explain.set_enabled(False)
        storm_pool = ("t.small", "zone-1a", wk.CAPACITY_TYPE_SPOT)
        try:
            spot_prev = spot_plane.set_enabled(True)
            baseline, base_meta = self._spot_noop_window({})
            spot_plane.set_enabled(False)
            before = spot_plane.activity()
            disabled, dis_meta = self._spot_noop_window({storm_pool: 0.9})
            after = spot_plane.activity()
            spot_plane.set_enabled(spot_prev)
        finally:
            _explain.set_enabled(expl_prev)
            _profiling.set_enabled(prof_prev)

        evidence = {"noop": {"enabled": False,
                             "before": before, "after": after}}
        violations = invariants.check_spot_noop(evidence["noop"])
        if disabled != baseline:
            drift = sorted(k for k in baseline
                           if baseline[k] != disabled[k])
            violations.append(invariants.Violation(
                "spot-strict-noop",
                f"solve decisions with the plane DISABLED diverge from "
                f"the quiet-baseline window in {drift} — disabling the "
                "plane must be bit-identical to a build without it"))
        return {
            "seed": self.seed,
            "scenario": scenario,
            "drill": "spot-noop",
            "cycles": self.SPOT_NOOP_CYCLES,
            "workload_pods": 12,
            "machines_launched": len(baseline["machines"]),
            "decisions_identical": disabled == baseline,
            "spot": {"noop": {
                "enabled": False,
                "deltas": {k: after[k] - before[k] for k in before},
            }},
            "controller_errors": base_meta["errors"] + dis_meta["errors"],
            "violations": [v.as_dict() for v in violations],
            "passed": not violations,
        }

    def run_spot_storm_drill(self) -> dict:
        t0 = time.time()
        self._bundles = []
        n_nodes = self.spot_storm_nodes or self.SPOT_STORM_NODES
        n_reclaims = self.spot_storm_reclaims or self.SPOT_STORM_RECLAIMS
        scenarios = [
            self.run_spot_storm_scenario(0, n_nodes, n_reclaims),
            self.run_spot_wrong_forecast(1),
            self.run_spot_noop(2),
        ]
        storm = scenarios[0]
        artifact = {
            "tool": "karpenter_tpu.chaos",
            "mode": "spot-storm",
            "seed": self.seed,
            "nodes": n_nodes,
            "reclaims": n_reclaims,
            "restore_bound_cycles": self.SPOT_RESTORE_K,
            "scenario_count": len(scenarios),
            "passed": all(s["passed"] for s in scenarios),
            "key_numbers": {
                "fleet_nodes": storm["fleet"]["nodes"],
                "storm_reclaims": storm["storm"]["reclaims_delivered"],
                "restore_cycles": storm["storm"]["restore_cycles"],
                "proactive_rebalances": len(
                    storm["rebalance"]["ledger"]),
                "post_storm_launches_into_stormed_pool":
                    storm["composition"][
                        "post_storm_launches_into_stormed_pool"],
                "risk_decision_records":
                    storm["composition"]["risk_decision_records"],
                "hourly_cost_before": storm["fleet"]["hourly_cost_before"],
                "hourly_cost_after": storm["hourly_cost_after"],
                "wrong_forecast_post_clear_launches":
                    scenarios[1]["post_clear_launches"],
                "noop_decisions_identical":
                    scenarios[2]["decisions_identical"],
            },
            "scenarios": scenarios,
            # volatile fields below this line only (replay contract)
            "duration_s": round(time.time() - t0, 3),
            "bundles": list(self._bundles),
        }
        if self.out_dir:
            os.makedirs(self.out_dir, exist_ok=True)
            path = os.path.join(self.out_dir,
                                f"spotstorm_seed{self.seed}.json")
            with open(path, "w") as f:
                json.dump(artifact, f, indent=2, sort_keys=True)
            artifact["artifact_path"] = path
        return artifact

    # -- artifact --------------------------------------------------------------

    def run(self) -> dict:
        if self.crash:
            return self.run_crash_drill()
        if self.storm:
            return self.run_storm()
        if self.partition:
            return self.run_partition_drill()
        if self.spot_storm:
            return self.run_spot_storm_drill()
        t0 = time.time()
        self._bundles = []
        scenarios = [self.run_scenario(s) for s in range(self.scenarios)]
        kinds = sorted({k for s in scenarios for k in s["fired_kinds"]})
        artifact = {
            "tool": "karpenter_tpu.chaos",
            "seed": self.seed,
            "burst": self.burst,
            "scenario_count": self.scenarios,
            "fault_kinds": kinds,
            "layers": sorted({LAYER_OF_KIND[k] for k in kinds}),
            "passed": all(s["passed"] for s in scenarios),
            "scenarios": scenarios,
            # volatile fields below this line only — scenario dicts must
            # stay a pure function of the seed (replay contract)
            "duration_s": round(time.time() - t0, 3),
            "bundles": list(self._bundles),
        }
        if self.out_dir:
            os.makedirs(self.out_dir, exist_ok=True)
            stem = "chaos_burst" if self.burst else "chaos"
            path = os.path.join(self.out_dir,
                                f"{stem}_seed{self.seed}.json")
            with open(path, "w") as f:
                json.dump(artifact, f, indent=2, sort_keys=True)
            artifact["artifact_path"] = path
        return artifact
