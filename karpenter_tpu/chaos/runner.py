"""Chaos scenario driver: seed -> plan -> faulted convergence -> verdict.

One scenario assembles a fresh hermetic operator (FakeClock, FakeCloud,
in-process kube store), installs the injector, and drives reconcile
cycles in two phases:

  chaos phase   — CHAOS_CYCLES cycles with faults armed. Every cycle
                  consults the cycle sites (ICE, spot burst, clock skew,
                  watch reset), runs each controller once (exceptions
                  logged, never fatal — crashing on an injected fault is
                  itself a finding), and lets the workload "ReplicaSet"
                  replace drained pods.
  settle phase  — faults disarmed; cycles continue until quiescence or a
                  step deadline, then the clock jumps past the GC grace
                  window so leak reaping can run, then a final settle.

After convergence the cross-layer invariants run and the scenario emits
a JSON-serializable dict. Everything inside a scenario dict is a pure
function of (seed, scenario) — that is the replay contract the tests
assert — so volatile fields (wall-clock duration) live only at the
artifact top level.
"""

from __future__ import annotations

import json
import logging
import os
import time

from ..apis import wellknown as wk
from ..apis.nodetemplate import NodeTemplate
from ..apis.provisioner import Provisioner
from ..apis.settings import Settings
from ..fake.cloud import FakeCloud
from ..models import machine as machine_model
from ..models.instancetype import Catalog, make_instance_type
from ..models.pod import make_pod
from ..models.requirements import OP_IN, Requirements
from ..operator import Operator
from ..utils.clock import FakeClock
from . import invariants
from .plan import LAYER_OF_KIND, ChaosRng, FaultPlan
from .inject import ChaosInjector

log = logging.getLogger("karpenter.chaos")


def chaos_catalog() -> Catalog:
    """Small mixed catalog: enough shape diversity for consolidation to
    have real choices, small enough that scenarios stay fast."""
    return Catalog(types=[
        make_instance_type("t.small", cpu=2, memory="2Gi",
                           od_price=0.05, spot_price=0.02),
        make_instance_type("m.large", cpu=4, memory="16Gi",
                           od_price=0.20, spot_price=0.07),
        make_instance_type("m.xlarge", cpu=16, memory="64Gi",
                           od_price=0.80, spot_price=0.28),
    ])


class ChaosRunner:
    CHAOS_CYCLES = 18          # > FaultPlan.CYCLE_HORIZON so every cycle fault can land
    SETTLE_DEADLINE = 30       # settle cycles before declaring non-quiescence
    CYCLE_SECONDS = 30.0

    def __init__(self, seed: int, scenarios: int = 1, wire: bool = False,
                 intensity: float = 1.0, out_dir: "str | None" = None,
                 burst: bool = False):
        self.seed = seed
        self.scenarios = scenarios
        self.wire = wire
        self.intensity = intensity
        self.out_dir = out_dir
        # burst mode swaps the sampled schedule for FaultPlan.burst — the
        # dense cloud-5xx + solver-crash window that exercises the
        # resilience plane (breakers, budgets, ladders) hard enough for
        # its invariants to have teeth
        self.burst = burst
        # diagnostics bundles auto-dumped by failed scenarios (volatile:
        # paths depend on out_dir, so they live at the artifact top level,
        # never inside a scenario dict)
        self._bundles: "list[str]" = []

    # -- assembly --------------------------------------------------------------

    def _build(self, clock: FakeClock):
        catalog = chaos_catalog()
        cloud = FakeCloud(catalog=catalog, clock=clock)
        settings = Settings(cluster_name="chaos",
                            cluster_endpoint="https://chaos.example",
                            batch_idle_duration=0.0, batch_max_duration=0.0,
                            interruption_queue_name="chaos-q")
        op = Operator(cloud, settings, catalog, clock=clock)
        op.kube.create("nodetemplates", "default", NodeTemplate(
            name="default",
            subnet_selector={
                "id": "subnet-zone-1a,subnet-zone-1b,subnet-zone-1c"},
            security_group_selector={"id": "sg-default"}))
        op.cloudprovider.register_nodetemplate(
            op.kube.get("nodetemplates", "default"))
        prov = Provisioner(
            name="default", provider_ref="default",
            consolidation_enabled=True,
            requirements=Requirements.of(
                (wk.LABEL_CAPACITY_TYPE, OP_IN,
                 [wk.CAPACITY_TYPE_SPOT, wk.CAPACITY_TYPE_ON_DEMAND])))
        prov.set_defaults()
        prov.validate()
        op.kube.create("provisioners", "default", prov)
        return op, cloud

    def _workload(self, plan: FaultPlan) -> "dict[str, dict]":
        """Derive the steady workload from the plan's PRNG family so every
        scenario stresses a different shape — deterministically."""
        r = ChaosRng((plan.seed << 8) ^ plan.scenario).fork("workload")
        n = r.randint(6, 12)
        sizes = (("1", "2Gi"), ("2", "4Gi"), ("500m", "1Gi"))
        return {f"w{i}": {"cpu": c, "memory": m}
                for i in range(n)
                for c, m in (r.choice(sizes),)}

    def _reconcile_workload(self, op, workload, injector) -> None:
        """ReplicaSet analogue: pods drained by termination (the store
        deletes them) or orphaned on a reaped node come back as fresh
        unbound pods. Harness traffic must not consume fault indices."""
        with injector.paused():
            for name, shape in workload.items():
                obj = op.kube.get("pods", name)
                if obj is not None and obj.node_name \
                        and obj.node_name not in op.cluster.nodes:
                    op.kube.delete("pods", name)
                    obj = None
                if obj is None:
                    op.kube.create("pods", name, make_pod(name, **shape))

    # -- driving ---------------------------------------------------------------

    _CONTROLLERS = ("settingswatch", "nodetemplate", "machinehydration",
                    "provisioning", "machinelifecycle", "interruption",
                    "deprovisioning", "termination", "counters",
                    "garbagecollection")

    def _drive_once(self, op, errors: "list[str]") -> None:
        """reconcile_all_once + GC, but each controller individually
        fenced: an injected fault escaping a controller's own error
        handling is recorded, not fatal."""
        for name in self._CONTROLLERS:
            ctrl = getattr(op, name)
            if ctrl is None:
                continue
            try:
                ctrl.reconcile_once()
            except Exception as e:  # noqa: BLE001 — the fence is the point
                errors.append(f"{name}: {type(e).__name__}: {e}")
        # introspection rides every drive: the flight recorder's snapshot
        # ring gets per-cycle history and the deadman sees crash-looping
        # controllers (their failed cycles never refresh the heartbeat)
        op.flightrecorder.record_snapshot()
        op.watchdog.check()

    def _quiescent(self, op) -> bool:
        if op.kube.pending_pods():
            return False
        if any(n.marked_for_deletion for n in op.cluster.nodes.values()):
            return False
        if getattr(op.deprovisioning, "_pending_replace", None):
            return False
        for m in op.kube.machines():
            if m.status.state != machine_model.INITIALIZED:
                return False
        return True

    # -- one scenario ----------------------------------------------------------

    def run_scenario(self, scenario: int) -> dict:
        if self.burst:
            plan = FaultPlan.burst(self.seed, scenario)
        else:
            plan = FaultPlan.from_seed(self.seed, scenario,
                                       wire=False, intensity=self.intensity)
        injector = ChaosInjector(plan)
        clock = FakeClock()
        op, cloud = self._build(clock)
        # retry backoffs must advance the FAKE clock: a real time.sleep
        # under FakeClock would deadlock the single-threaded drive
        op.resilience.use_virtual_sleep()
        workload = self._workload(plan)
        errors: "list[str]" = []
        try:
            injector.install(op, cloud)
            self._reconcile_workload(op, workload, injector)
            for cycle in range(self.CHAOS_CYCLES):
                injector.on_cycle(op, cloud, cycle)
                self._drive_once(op, errors)
                self._reconcile_workload(op, workload, injector)
                clock.step(self.CYCLE_SECONDS)

            # settle: disarm, clear injected weather, converge
            injector.enabled = False
            for pool in list(injector._ice_expiry):
                cloud.insufficient_capacity_pools.discard(pool)
            injector._ice_expiry.clear()
            settle_cycles = 0
            for _ in range(self.SETTLE_DEADLINE):
                settle_cycles += 1
                self._drive_once(op, errors)
                self._reconcile_workload(op, workload, injector)
                clock.step(self.CYCLE_SECONDS)
                if self._quiescent(op):
                    break
            # leak reaping: jump past the GC grace window twice (both GC
            # directions carry their own eventual-consistency window),
            # then a short post-GC settle for any termination it queued
            for _ in range(2):
                clock.step(360.0)
                self._drive_once(op, errors)
            for _ in range(6):
                self._drive_once(op, errors)
                self._reconcile_workload(op, workload, injector)
                clock.step(self.CYCLE_SECONDS)
                if self._quiescent(op):
                    break

            # resilience-plane evidence (breaker ledgers, budget water
            # marks, ladder transitions) — captured before stop() and fed
            # to the structural invariants
            resilience_evidence = op.resilience.evidence()
            violations = invariants.check_all(
                op, cloud,
                token_launches=injector.token_launches,
                consolidation_actions=injector.consolidation_actions,
                resilience=resilience_evidence)
            if not self._quiescent(op):
                violations = [invariants.Violation(
                    "quiescence",
                    "scenario never reached quiescence before the step "
                    "deadline")] + violations
            # a failed seed dumps a diagnostics bundle next to its replay
            # artifact: the snapshot ring, logs, traces and events from the
            # exact cycles that broke the invariant (deterministic path —
            # replaying the seed overwrites the same file)
            if violations and self.out_dir:
                os.makedirs(self.out_dir, exist_ok=True)
                bundle_path = os.path.join(
                    self.out_dir,
                    f"chaos_seed{self.seed}_s{scenario}_bundle.json")
                written = op.flightrecorder.trigger(
                    "chaos_invariant_breach",
                    detail="; ".join(
                        f"[{v.invariant}] {v.message}"
                        for v in violations)[:500],
                    force=True, path=bundle_path)
                if written:
                    self._bundles.append(written)
        finally:
            op.stop()

        fired_kinds = sorted(injector.fired_kinds())
        return {
            "seed": self.seed,
            "scenario": scenario,
            "workload_pods": len(workload),
            "plan": plan.describe(),
            "fired": list(injector.fired),
            "site_counts": injector.site_counts(),
            "fired_kinds": fired_kinds,
            "layers": sorted({LAYER_OF_KIND[k] for k in fired_kinds}),
            "controller_errors": errors,
            "consolidation_actions": len(injector.consolidation_actions),
            "settle_cycles": settle_cycles,
            "final_nodes": len(op.cluster.nodes),
            "resilience": resilience_evidence,
            "violations": [v.as_dict() for v in violations],
            "passed": not violations,
        }

    # -- artifact --------------------------------------------------------------

    def run(self) -> dict:
        t0 = time.time()
        self._bundles = []
        scenarios = [self.run_scenario(s) for s in range(self.scenarios)]
        kinds = sorted({k for s in scenarios for k in s["fired_kinds"]})
        artifact = {
            "tool": "karpenter_tpu.chaos",
            "seed": self.seed,
            "burst": self.burst,
            "scenario_count": self.scenarios,
            "fault_kinds": kinds,
            "layers": sorted({LAYER_OF_KIND[k] for k in kinds}),
            "passed": all(s["passed"] for s in scenarios),
            "scenarios": scenarios,
            # volatile fields below this line only — scenario dicts must
            # stay a pure function of the seed (replay contract)
            "duration_s": round(time.time() - t0, 3),
            "bundles": list(self._bundles),
        }
        if self.out_dir:
            os.makedirs(self.out_dir, exist_ok=True)
            stem = "chaos_burst" if self.burst else "chaos"
            path = os.path.join(self.out_dir,
                                f"{stem}_seed{self.seed}.json")
            with open(path, "w") as f:
                json.dump(artifact, f, indent=2, sort_keys=True)
            artifact["artifact_path"] = path
        return artifact
