"""IncrementalSolver: delta-aware solve orchestration with a bit-parity
audit and a full-solve escape hatch.

Wraps any base solve callable (the provisioning controller's routed
ladder, the oracle in tests) behind two new gap-ledger phases:

* ``extract``    — dirty bookkeeping + the escape gate (cold cursor,
  deletion-log gap, dirty set past the churn threshold, entangled group)
* ``warm_start`` — resident mask patch (O(dirty x specs)), neighborhood
  selection, subproblem assembly, HBM ``assignment`` residency accounting

The small solve runs the base callable on the subproblem snapshot; the
scalar oracle then re-solves THE SAME subproblem and the two decision
fingerprints must match bit-for-bit (``incremental-parity-never-
diverges``). Any divergence — or any escape — falls back to the legacy
full solve, so the plane can only ever cost correctness nothing.

Both phases appear in the Tracer PHASE_REGISTRY and the gap ledger's
phase table, so "encode cost proportional to churn, not fleet size" is a
ledger-attributable claim, not a log line.
"""
from __future__ import annotations

import os
import threading
import time
from typing import Optional

from ..profiling.gapledger import GAP_LEDGER
from ..tracing import TRACER
from . import state
from .extract import (ESCAPE_AUDIT_DIVERGENCE, ESCAPE_REASONS, DeltaTracker,
                      check_escape, select_neighborhood)
from .resident import ResidentMasks, account_residency

AUDIT_ENV = "KARPENTER_TPU_INCREMENTAL_AUDIT"

# plane-global monotone activity counters (chaos strict-noop diffs these)
_lock = threading.Lock()
_COUNTS = {
    "cycles": 0,
    "incremental_solves": 0,
    "full_solves": 0,
    "escape_trips": 0,
    "audit_divergences": 0,
    "extracted_rows": 0,
    "mask_patches": 0,
}
_ESCAPES = {reason: 0 for reason in ESCAPE_REASONS}


def _bump(**deltas) -> None:
    with _lock:
        for key, d in deltas.items():
            _COUNTS[key] += d


def _bump_escape(reason: str) -> None:
    with _lock:
        _ESCAPES[reason] = _ESCAPES.get(reason, 0) + 1


def counters() -> dict:
    with _lock:
        out = dict(_COUNTS)
        out.update({f"escape_{k.replace('-', '_')}": v
                    for k, v in _ESCAPES.items()})
        return out


def audit_enabled() -> bool:
    return os.environ.get(AUDIT_ENV, "1").strip().lower() \
        not in ("0", "false", "off", "no")


def solve_fingerprint(result) -> tuple:
    """Decision identity of a SolveResult: new-node decisions, per-node
    existing placements, unschedulable count. Two solves agreeing here
    bind the same pods to the same capacity."""
    return (tuple(result.decisions()),
            tuple(sorted((n, c) for n, c in result.existing_counts.items()
                         if c)),
            result.unschedulable_count())


def oracle_fingerprint(catalog, provisioners, pods, existing,
                       overhead=None) -> tuple:
    """The scalar oracle's fingerprint on the same (sub)problem."""
    from ..oracle.scheduler import Scheduler

    sched = Scheduler(catalog, provisioners, overhead)
    res = sched.schedule(list(pods), existing=existing)
    return (tuple(res.node_decisions(sched.options)),
            tuple(sorted((n, len(ps))
                         for n, ps in res.existing_assignments.items()
                         if ps)),
            len(res.unschedulable))


class IncrementalSolver:
    """One per consumer (the provisioning controller owns one). Not
    thread-safe by design: the owning reconcile loop is single-threaded,
    matching the solver caches it sits beside."""

    def __init__(self, cluster, *, threshold: "Optional[float]" = None):
        self.cluster = cluster
        self.tracker = DeltaTracker(cluster)
        self.masks = ResidentMasks(cluster)
        self.threshold = threshold
        self.last: "Optional[dict]" = None  # statusz / debug surface

    # -- the one entry point ------------------------------------------------

    def solve(self, pods, full_existing, base, *, catalog=None,
              provisioners=None, overhead=None):
        """base(pods, existing) -> (SolveResult, kind). Returns the same
        pair. With the plane disabled this method must not run (callers
        gate on state.enabled()); it still degrades to a bare full solve
        if reached, touching no counters."""
        if not state.enabled():
            return base(pods, full_existing)
        from ..models.pod import group_pods

        with GAP_LEDGER.solve_scope("solver"):
            seq0 = self.cluster.seq
            t0 = time.perf_counter()
            groups = group_pods(list(pods))
            reason, dirty = check_escape(groups, full_existing, self.tracker,
                                         self.threshold)
            dt = time.perf_counter() - t0
            TRACER.record_span("solver.extract", dt)
            GAP_LEDGER.note("extract", dt, lane="encode")
            _bump(cycles=1, extracted_rows=len(dirty))
            if reason is not None:
                return self._full_solve(pods, full_existing, base, reason,
                                        seq0, dirty)

            t0 = time.perf_counter()
            patched = self.masks.sync([g.spec for g in groups])
            sub = select_neighborhood(self.cluster, groups, full_existing,
                                      dirty, masks=self.masks)
            resident_bytes = account_residency(self.masks)
            dt = time.perf_counter() - t0
            TRACER.record_span("solver.warm_start", dt,
                               patched_rows=patched,
                               sub_nodes=len(sub.existing),
                               full_nodes=sub.full_nodes)
            GAP_LEDGER.note("warm_start", dt, lane="encode")
            _bump(mask_patches=patched)

            result, kind = base(pods, sub.existing)
            if (audit_enabled() and catalog is not None
                    and provisioners is not None):
                want = oracle_fingerprint(catalog, provisioners, pods,
                                          sub.existing, overhead)
                got = solve_fingerprint(result)
                if want != got:
                    _bump(audit_divergences=1)
                    return self._full_solve(pods, full_existing, base,
                                            ESCAPE_AUDIT_DIVERGENCE, seq0,
                                            dirty)
            self.tracker.advance(seq0)
            _bump(incremental_solves=1)
            self.last = {
                "mode": "incremental",
                "dirty_nodes": len(dirty),
                "sub_nodes": len(sub.existing),
                "full_nodes": sub.full_nodes,
                "shrink": round(sub.shrink, 5),
                "patched_rows": patched,
                "resident_bytes": resident_bytes,
                "kind": kind,
            }
            return result, kind

    def _full_solve(self, pods, full_existing, base, reason, seq0, dirty):
        _bump(full_solves=1, escape_trips=1)
        _bump_escape(reason)
        result, kind = base(pods, full_existing)
        # the full solve re-establishes coherence as of seq0; mutations
        # landed after the capture (the solve's own binds) stay dirty
        self.tracker.advance(seq0)
        self.last = {
            "mode": "full",
            "escape": reason,
            "dirty_nodes": len(dirty),
            "full_nodes": len(full_existing),
            "kind": kind,
        }
        return result, kind
