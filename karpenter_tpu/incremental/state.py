"""Global on/off switch for the incremental solving plane.

The incremental plane is advisory-never-load-bearing (same contract as the
profiling/explain/membership planes): every producer — the delta tracker,
the resident mask/candidate patchers, the subproblem solver — checks
:func:`enabled` before doing ANY work, so disabling the plane is a strict
no-op (zero counters, zero resident arrays, every solve is the legacy full
solve). The chaos drill enforces exactly that invariant
(``incremental-strict-noop``), and the parity audit inside the plane
enforces the stronger one: whenever it IS on, its decisions are
bit-identical to the full solve (``incremental-parity-never-diverges``).

Default is ON (the plane exists to carry the steady-state cycle);
``KARPENTER_TPU_INCREMENTAL=0`` (or ``false``/``off``/``no``) disables it
at process start, and :func:`set_enabled` / :func:`disabled` flip it at
runtime (chaos drills, A/B overhead baselines).
"""
from __future__ import annotations

import contextlib
import os
import threading

FLAG_ENV = "KARPENTER_TPU_INCREMENTAL"
_FALSY = ("0", "false", "off", "no")

_lock = threading.Lock()
_enabled = os.environ.get(FLAG_ENV, "1").strip().lower() not in _FALSY


def enabled() -> bool:
    return _enabled


def set_enabled(on: bool) -> bool:
    """Flip the plane; returns the previous state (restore token)."""
    global _enabled
    with _lock:
        prev = _enabled
        _enabled = bool(on)
        return prev


@contextlib.contextmanager
def disabled():
    """Scoped hard-off: A/B baselines and the chaos strict-noop drill."""
    prev = set_enabled(False)
    try:
        yield
    finally:
        set_enabled(prev)
