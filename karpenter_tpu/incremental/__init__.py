"""Incremental solving plane: delta-aware resident repacking.

Makes per-cycle solve cost proportional to CHURN, not fleet size. The
columnar cluster state already stamps every mutated row with a monotone
``changed_seq``; this plane keeps three derived structures resident
between cycles and patches them only at dirty rows:

* :class:`ResidentMasks` — per-spec existing-node fit masks (the mask
  fold that costs ~145 ms/cycle at 100k nodes when rebuilt from scratch)
* :class:`ResidentCandidates` — consolidation-eligibility verdicts (the
  ~407 ms/cycle candidate sweep)
* the :class:`IncrementalSolver` — extracts the dirty subproblem (changed
  rows + per-group feasible prefixes), warm-starts a small solve on it,
  and audits the result against the scalar oracle at bit parity, with a
  full-solve escape hatch (cold start, churn threshold, entangled
  constraints, deletion-log gap, audit divergence)

Strict-noop contract: with ``KARPENTER_TPU_INCREMENTAL=0`` nothing here
runs and no counter moves (chaos invariant ``incremental-strict-noop``);
while enabled, decisions are bit-identical to the full solve
(``incremental-parity-never-diverges``).
"""
from __future__ import annotations

from .extract import (DEFAULT_MAX_DIRTY_FRAC, ESCAPE_AUDIT_DIVERGENCE,
                      ESCAPE_COLD_START, ESCAPE_DELETION_LOG_GAP,
                      ESCAPE_DIRTY_THRESHOLD, ESCAPE_ENTANGLED_GROUP,
                      ESCAPE_REASONS, MAX_DIRTY_FRAC_ENV, DeltaTracker,
                      Subproblem, check_escape, entangled,
                      extract_subproblem, max_dirty_frac,
                      select_neighborhood)
from .resident import (ResidentCandidates, ResidentMasks, account_residency,
                       empty_node_rows, expired_node_rows)
from .solver import (AUDIT_ENV, IncrementalSolver, audit_enabled, counters,
                     oracle_fingerprint, solve_fingerprint)
from .state import FLAG_ENV, disabled, enabled, set_enabled

__all__ = [
    "AUDIT_ENV", "DEFAULT_MAX_DIRTY_FRAC", "DeltaTracker",
    "ESCAPE_AUDIT_DIVERGENCE", "ESCAPE_COLD_START",
    "ESCAPE_DELETION_LOG_GAP", "ESCAPE_DIRTY_THRESHOLD",
    "ESCAPE_ENTANGLED_GROUP", "ESCAPE_REASONS", "FLAG_ENV",
    "IncrementalSolver", "MAX_DIRTY_FRAC_ENV", "ResidentCandidates",
    "ResidentMasks", "Subproblem", "account_residency", "activity",
    "audit_enabled", "check_escape", "counters", "disabled", "enabled",
    "empty_node_rows", "entangled", "expired_node_rows",
    "extract_subproblem", "max_dirty_frac", "oracle_fingerprint",
    "select_neighborhood", "set_enabled", "solve_fingerprint",
]


def activity() -> "dict[str, int]":
    """Flat monotone counters for the chaos strict-noop diff: every number
    here must stay frozen while the plane is disabled."""
    return dict(counters())
