"""Persistent resident assignment state carried between solve cycles.

The full-solve path rebuilds three things from scratch every cycle, each
O(fleet): the per-group label/taint fit masks (``existing_fit_vector``
over a fresh snapshot), the consolidation candidate verdict sweep, and the
emptiness/expiration scans. The columnar state already tells us exactly
which rows changed (``changed_seq``), so this module keeps those
structures RESIDENT in cluster row space and patches them only at dirty
rows — the host-side analogue of the device-resident catalog: encode cost
proportional to churn, not fleet size.

Residency is accounted: the arrays file under the ``assignment`` class of
the HBM ledger (solver/buckets.py) with REPLACE semantics — patching in
place never grows the footprint, so the ledger carries the actual bytes
held, exactly like the donated delta buffers.

Coherence contract (audited per cycle by the soak, property-tested in
tests/test_incremental.py): after ``sync()``, for every tracked spec and
any snapshot ``ex``, ``masks_for(ex)[key]`` is bit-identical to a fresh
``existing_fit_vector(ex, spec)``, and ``candidate_names()`` equals
``cluster.consolidation_candidates()``.
"""
from __future__ import annotations

from typing import Optional

import numpy as np

from ..models.cluster import ExistingColumns


def _mask_key(spec) -> tuple:
    """Identity of a fit mask: only requirements + tolerations feed
    ``existing_fit_vector``, so masks are shared across groups that differ
    only in resources/counts (the common deployment-scaling churn)."""
    return (spec.requirements.canonical(), spec.tolerations)


class ResidentMasks:
    """Per-spec node-fit masks in cluster ROW space, patched at dirty rows.

    Row space (not snapshot space) is the trick: snapshots reorder when
    membership changes, rows don't. A freed row keeps its stale mask bits
    harmlessly (never gathered — gathers go through ``ex.rows``, which only
    contains live rows), and row reuse is safe because ``add_node`` marks
    the reused row dirty.
    """

    def __init__(self, cluster):
        self.cluster = cluster
        self._cursor: "Optional[int]" = None  # None => cold, full build
        self._masks: "dict[tuple, np.ndarray]" = {}
        self._specs: "dict[tuple, object]" = {}
        # monotone activity counters (chaos strict-noop diffs these)
        self.patched_rows_total = 0
        self.full_builds_total = 0

    def nbytes(self) -> int:
        return sum(m.nbytes for m in self._masks.values())

    def _grow(self, capacity: int) -> None:
        for key, mask in self._masks.items():
            if len(mask) < capacity:
                grown = np.zeros(capacity, dtype=bool)
                grown[: len(mask)] = mask
                self._masks[key] = grown

    def _full_snapshot(self) -> ExistingColumns:
        """Row-space snapshot over ALL occupied rows, marked included —
        mask bits are maintained for every live row; whether a marked row
        participates in a solve is the gather's concern, not ours."""
        cols = self.cluster.columns
        rows = np.nonzero(cols.occupied)[0]
        names = [cols.name_of[r] for r in rows]
        return ExistingColumns(self.cluster, names, rows)

    def sync(self, specs) -> int:
        """Bring every tracked mask (plus any new `specs`) coherent with
        the cluster; returns the number of row-patches applied. Cold start
        (or first sight of a spec) pays one full fold; afterwards the cost
        is O(dirty rows x specs)."""
        from ..models.encode import existing_fit_vector

        cluster = self.cluster
        cols = cluster.columns
        seq0 = cluster.seq  # capture BEFORE folding: late writers re-patch
        self._grow(cols.capacity)
        fresh = []
        for spec in specs:
            key = _mask_key(spec)
            if key not in self._masks:
                fresh.append((key, spec))
                self._specs[key] = spec
        patched = 0
        if self._cursor is None or (fresh and not self._masks):
            full = self._full_snapshot()
            for key, spec in self._specs.items():
                mask = np.zeros(cols.capacity, dtype=bool)
                if len(full.rows):
                    mask[full.rows] = existing_fit_vector(full, spec)
                self._masks[key] = mask
                self.full_builds_total += 1
                patched += len(full.rows)
            self._cursor = seq0
            self.patched_rows_total += patched
            return patched
        if fresh:
            full = self._full_snapshot()
            for key, spec in fresh:
                mask = np.zeros(cols.capacity, dtype=bool)
                if len(full.rows):
                    mask[full.rows] = existing_fit_vector(full, spec)
                self._masks[key] = mask
                self.full_builds_total += 1
                patched += len(full.rows)
        dirty = np.nonzero(cols.occupied & (cols.changed_seq > self._cursor))[0]
        if len(dirty):
            names = [cols.name_of[r] for r in dirty]
            sub = ExistingColumns(cluster, names, dirty)
            for key, spec in self._specs.items():
                self._masks[key][dirty] = existing_fit_vector(sub, spec)
                patched += len(dirty)
        self._cursor = seq0
        self.patched_rows_total += patched
        return patched

    def mask_for(self, ex: ExistingColumns, spec) -> "Optional[np.ndarray]":
        """The spec's fit mask gathered into `ex` snapshot order, or None
        when the spec isn't resident (caller folds fresh)."""
        mask = self._masks.get(_mask_key(spec))
        if mask is None:
            return None
        if len(ex.rows) == 0:
            return np.zeros(0, dtype=bool)
        return mask[ex.rows]

    def drop(self) -> None:
        """Release all resident masks (escape-hatch full rebuild)."""
        self._masks.clear()
        self._specs.clear()
        self._cursor = None


class ResidentCandidates:
    """Consolidation-eligibility verdicts in row space, patched at dirty
    rows. The column prefilter (occupied/unmarked/initialized/non-empty/
    no-veto) stays a vectorized expression; only the expensive per-node
    evictability+PDB verdict (``node_consolidation_clear``) is cached here
    and recomputed for dirtied rows. A PDB-set change shifts verdicts on
    CLEAN rows too (shared headroom), so a pdb-epoch bump drops the cache
    wholesale."""

    def __init__(self, cluster):
        self.cluster = cluster
        self._cursor: "Optional[int]" = None
        self._clear = np.zeros(0, dtype=bool)
        self._pdb_epoch: "Optional[int]" = None
        self.patched_rows_total = 0
        self.full_builds_total = 0

    def nbytes(self) -> int:
        return int(self._clear.nbytes)

    def sync(self) -> int:
        """Patch verdicts for dirty rows; returns rows re-verdicted."""
        cluster = self.cluster
        cols = cluster.columns
        seq0 = cluster.seq
        if len(self._clear) < cols.capacity:
            grown = np.zeros(cols.capacity, dtype=bool)
            grown[: len(self._clear)] = self._clear
            self._clear = grown
        # epoch bumps lazily inside _pdb_index(); force it current FIRST or
        # a just-changed PDB set would leave clean rows' verdicts stale for
        # one cycle (epoch read old -> dirty-only patch -> bump mid-loop)
        cluster._pdb_index()
        epoch = cluster._pdb_epoch
        if self._cursor is None or epoch != self._pdb_epoch:
            rows = np.nonzero(cols.occupied)[0]
            self.full_builds_total += 1
        else:
            rows = np.nonzero(
                cols.occupied & (cols.changed_seq > self._cursor))[0]
        for r in rows:
            node = cluster.nodes.get(cols.name_of[r])
            self._clear[r] = (node is not None
                              and cluster.node_consolidation_clear(node))
        self._cursor = seq0
        self._pdb_epoch = epoch
        self.patched_rows_total += len(rows)
        return len(rows)

    def eligible_rows(self) -> np.ndarray:
        """Row indices passing the full gate (prefilter AND verdict) —
        one vectorized expression, no per-node Python."""
        cols = self.cluster.columns
        n = len(self._clear)
        gate = (cols.occupied[:n] & ~cols.marked[:n] & cols.initialized[:n]
                & (cols.non_daemon[:n] > 0) & ~cols.no_consolidate[:n]
                & self._clear[:n])
        return np.nonzero(gate)[0]

    def candidate_names(self, candidate_filter=None) -> "list[str]":
        """Name-sorted candidates — the parity twin of
        ``cluster.consolidation_candidates`` (which returns nodes)."""
        cols = self.cluster.columns
        names = sorted(cols.name_of[r] for r in self.eligible_rows())
        if candidate_filter is None:
            return names
        return [n for n in names
                if candidate_filter(self.cluster.nodes[n])]

    def drop(self) -> None:
        self._clear = np.zeros(0, dtype=bool)
        self._cursor = None
        self._pdb_epoch = None


def empty_node_rows(cluster, ttl_rows: "Optional[np.ndarray]" = None,
                    ) -> np.ndarray:
    """Vectorized emptiness set: occupied, unmarked, zero non-daemon pods.
    With `ttl_rows` (the per-row emptiness-TTL array the deprovisioner
    builds, nan = untracked) this is bit-identical to the emptiness
    sweep's `empty` mask."""
    cols = cluster.columns
    mask = cols.occupied & ~cols.marked & (cols.non_daemon == 0)
    if ttl_rows is not None:
        mask = mask & ~np.isnan(ttl_rows)
    return np.nonzero(mask)[0]


def expired_node_rows(cluster, ttl_rows: np.ndarray,
                      now: float) -> np.ndarray:
    """Vectorized expiration set against the per-row expiry-TTL array
    (nan = no expiry), mirroring reconcile_expiration's age test."""
    cols = cluster.columns
    with np.errstate(invalid="ignore"):
        mask = (cols.occupied & ~cols.marked
                & (now - cols.created_ts >= ttl_rows))
    return np.nonzero(mask)[0]


def account_residency(*residents) -> int:
    """File the resident arrays' bytes under the HBM ledger's
    ``assignment`` class (replace semantics — see HbmLedger.set_resident);
    returns the bytes filed."""
    from ..solver.buckets import HBM

    total = sum(r.nbytes() for r in residents)
    HBM.set_resident("incremental", "assignment", float(total))
    return total
