"""Dirty-subproblem extraction: changed rows + interference neighborhood.

Given the pending pods and a full snapshot, select the small set of
existing nodes that can possibly matter to THIS solve, so the solver
encodes a subproblem whose size tracks churn and demand rather than fleet
size — while staying DECISION-IDENTICAL to the full solve.

Soundness of the prefix selection: the solver (all rungs parity-match the
scalar oracle) walks existing nodes in name order and binds at most
``total_pods`` pods. Every node another group fills consumes at least one
of those pods, so for any group the full solve's existing-node placements
land within its first ``2 x total_pods`` nodes that pass (label/taint fit
AND one-pod headroom): at most ``total_pods`` feasible nodes can fill up
mid-solve, and the group itself lands on at most its own count — solves
only ADD pods, so a node without headroom now never gains any mid-solve. The union of those per-group prefixes (plus
every dirty node, which keeps recently-touched capacity in view for the
audit) therefore reproduces the full solve's placements exactly.

Groups carrying topology spread, zone anti-affinity, or inter-pod
(anti-)affinity terms are ENTANGLED: their feasibility depends on domain
population counts over nodes we'd exclude, so they escape to the full
solve rather than risk a divergence (docs/troubleshooting.md runbook
"Why did the full-solve escape hatch fire?").
"""
from __future__ import annotations

import dataclasses
import os
from typing import Optional

import numpy as np

from ..models.cluster import ExistingColumns

MAX_DIRTY_FRAC_ENV = "KARPENTER_TPU_INCREMENTAL_MAX_DIRTY_FRAC"
DEFAULT_MAX_DIRTY_FRAC = 0.25

# escape-hatch reason vocabulary (the runbook documents each)
ESCAPE_COLD_START = "cold-start"
ESCAPE_DIRTY_THRESHOLD = "dirty-set-threshold"
ESCAPE_ENTANGLED_GROUP = "entangled-group"
ESCAPE_DELETION_LOG_GAP = "deletion-log-gap"
ESCAPE_AUDIT_DIVERGENCE = "audit-divergence"
ESCAPE_REASONS = (ESCAPE_COLD_START, ESCAPE_DIRTY_THRESHOLD,
                  ESCAPE_ENTANGLED_GROUP, ESCAPE_DELETION_LOG_GAP,
                  ESCAPE_AUDIT_DIVERGENCE)


def max_dirty_frac() -> float:
    raw = os.environ.get(MAX_DIRTY_FRAC_ENV)
    if raw is None:
        return DEFAULT_MAX_DIRTY_FRAC
    try:
        val = float(raw)
    except ValueError:
        return DEFAULT_MAX_DIRTY_FRAC
    return val if 0.0 < val <= 1.0 else DEFAULT_MAX_DIRTY_FRAC


def entangled(spec) -> bool:
    """Constraints whose feasibility reads global domain counts — not
    separable onto a node subset (hostname anti-affinity is fine: its cap
    is per-node local)."""
    return bool(spec.topology or spec.pod_affinity or spec.pod_anti_affinity
                or spec.anti_affinity_zone or spec.anti_affinity_hostname)


@dataclasses.dataclass
class Subproblem:
    """The dirty subproblem: all pending pods against the selected
    existing-node neighborhood (a snapshot-order subset of `full`)."""
    existing: ExistingColumns
    dirty_names: "list[str]"
    full_nodes: int
    escape: "Optional[str]" = None  # set => caller must full-solve

    @property
    def shrink(self) -> float:
        """Existing-node reduction factor (1.0 = no shrink)."""
        if self.full_nodes == 0:
            return 1.0
        return len(self.existing) / self.full_nodes


class DeltaTracker:
    """Per-solver cursor over the cluster's mutation sequence. One tracker
    per consumer (provisioning solver, soak harness) — cursors are consumer
    state, not cluster state."""

    def __init__(self, cluster):
        self.cluster = cluster
        self.cursor: "Optional[int]" = None

    def advance(self, seq: "Optional[int]" = None) -> None:
        self.cursor = self.cluster.seq if seq is None else seq

    def dirty_names(self) -> "tuple[list[str], bool]":
        """(changed node names since the cursor, deletion-log-complete).
        Incomplete means deletions beyond the bounded log horizon — the
        caller must treat the whole fleet as dirty."""
        if self.cursor is None:
            return [], False
        names = self.cluster.dirty_since(self.cursor)
        deleted, complete = self.cluster.deleted_since(self.cursor)
        return names, complete


def check_escape(groups, full: ExistingColumns, tracker: DeltaTracker,
                 threshold: "Optional[float]" = None,
                 ) -> "tuple[Optional[str], list[str]]":
    """The extract-phase escape gate: (reason or None, dirty node names).
    Cheap by construction — dirty bookkeeping and spec flag tests only."""
    if tracker.cursor is None:
        return ESCAPE_COLD_START, []
    dirty, complete = tracker.dirty_names()
    if not complete:
        return ESCAPE_DELETION_LOG_GAP, dirty
    limit = max_dirty_frac() if threshold is None else threshold
    if len(full) and len(dirty) / len(full) > limit:
        return ESCAPE_DIRTY_THRESHOLD, dirty
    if any(entangled(g.spec) for g in groups):
        return ESCAPE_ENTANGLED_GROUP, dirty
    return None, dirty


def select_neighborhood(cluster, groups, full: ExistingColumns,
                        dirty: "list[str]",
                        masks: "Optional[object]" = None) -> Subproblem:
    """The warm-start-phase neighborhood gather (escape gate already
    passed): per-group feasible prefixes off the resident masks, plus the
    dirty nodes."""
    from ..models.encode import existing_fit_vector

    total_pods = sum(g.count for g in groups)
    # a group walks past at most total_pods nodes that THIS solve filled,
    # and lands on at most its own count of nodes — 2x covers both
    depth = 2 * total_pods
    n = len(full)
    keep = np.zeros(n, dtype=bool)
    if n and total_pods:
        # one-pod headroom per group: alloc - used >= one pod's vector
        free = full.alloc_rows - full.used_rows
        for g in groups:
            fit = None if masks is None else masks.mask_for(full, g.spec)
            if fit is None:
                fit = existing_fit_vector(full, g.spec)
            vec = np.asarray(g.spec.resource_vector(), dtype=np.int64)
            ok = np.nonzero(fit & np.all(free >= vec, axis=1))[0]
            keep[ok[:depth]] = True
    # dirty nodes ride along: recently-touched capacity stays in view and
    # the audit subproblem covers exactly the churned neighborhood
    if dirty:
        pos = {name: i for i, name in enumerate(full.names)}
        for name in dirty:
            i = pos.get(name)
            if i is not None:
                keep[i] = True
    idx = np.nonzero(keep)[0]
    names = [full.names[i] for i in idx]
    rows = full.rows[idx] if n else np.zeros(0, dtype=np.int64)
    return Subproblem(existing=ExistingColumns(cluster, names, rows),
                      dirty_names=dirty, full_nodes=n)


def extract_subproblem(cluster, groups, full: ExistingColumns,
                       tracker: DeltaTracker,
                       masks: "Optional[object]" = None,
                       threshold: "Optional[float]" = None) -> Subproblem:
    """check_escape + select_neighborhood in one call (test surface)."""
    reason, dirty = check_escape(groups, full, tracker, threshold)
    if reason is not None:
        return Subproblem(existing=full, dirty_names=dirty,
                          full_nodes=len(full), escape=reason)
    return select_neighborhood(cluster, groups, full, dirty, masks)
