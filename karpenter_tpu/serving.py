"""Operator HTTP serving plane: metrics, health, admission webhook.

Parity target: the reference serves Prometheus metrics on :8080, health
probes on :8081 and webhooks on :8443 (charts/karpenter/values.yaml:134-142,
probed by the deployment's liveness/readiness checks); the knative webhook
half answers AdmissionReview requests (pkg/webhooks/webhooks.go:33-63).

Three tiny stdlib servers (one per port so the chart's port wiring maps
1:1). The webhook endpoint implements the VALIDATING half of
admission.k8s.io/v1 AdmissionReview: objects parse through the same serde
the coordination plane uses, then run the in-process Webhooks pipeline —
deny returns allowed=false with the message; requests without a readable
body FAIL CLOSED. The apiserver always dials webhooks over TLS, so the
webhook listener wraps its socket when a cert/key pair is provided
(cert-manager mounts them in the deployment; plaintext only suits the mini
apiserver / local drives). Defaulting stays at the store boundary
(HttpKubeStore/KubeStore apply it before writes); mutating webhooks would
additionally need JSONPatch plumbing.
"""

from __future__ import annotations

import json
import logging
import os
import ssl
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional

log = logging.getLogger("karpenter.serving")

# /debug/traces ?limit= ceiling: the TRACER ring holds ~200 traces, so a
# larger ask only serializes the same data with more zeros
MAX_TRACE_LIMIT = 200

# /debug/profilez ?n= ceiling: distinct folded stacks worth serializing —
# beyond this the tail is single-sample noise
MAX_PROFILE_STACKS = 500

# /debug/criticalz ?n= ceiling: the critical ledger's ring default — a
# larger ask only re-serializes the same tail
MAX_CRITICAL_ROWS = 256

# /debug/decisions ?limit= and /debug/bundle ?decisions= ceiling: the
# explain ring defaults to 256 resident records — a larger ask only
# re-serializes the same tail
MAX_DECISIONS = 256

# /eventz ?n= ceiling: the recorder's post-dedupe ring bound
MAX_EVENTS = 1000


def clamped_int_param(qs: dict, key: str, default: int,
                      ceiling: int) -> "Optional[int]":
    """Shared /debug listing-param discipline (/debug/traces ?limit=,
    /debug/profilez ?n=): a non-integer returns None — the caller answers
    400, because a silent default would make a bad dashboard query look
    like a tiny ring — and a well-formed value clamps into [1, ceiling]."""
    try:
        value = int(qs.get(key, [str(default)])[0])
    except ValueError:
        return None
    return min(max(value, 1), ceiling)

# AdmissionReview resource plural -> store kind
_PLURALS = {
    "provisioners": "provisioners",
    "nodetemplates": "nodetemplates",
    "awsnodetemplates": "nodetemplates",  # backwards-compat manifests
}


class ServingPlane:
    """Owns the three listeners; start() returns the bound ports.

    Port 0 requests an ephemeral bind (N replica subprocesses on one host
    never collide); the ACTUAL bound ports are returned by start() and
    kept on `self.bound` so replica registration can hand the resolved
    address to the rendezvous handshake (fleet/replica.py)."""

    def __init__(self, operator, metrics_port: int = 8080,
                 health_port: int = 8081, webhook_port: int = 8443,
                 tls_cert: Optional[str] = None,
                 tls_key: Optional[str] = None):
        self.operator = operator
        self.ports = {"metrics": metrics_port, "health": health_port,
                      "webhook": webhook_port}
        self.tls_cert, self.tls_key = tls_cert, tls_key
        self.bound: "dict[str, int]" = {}
        self._servers: "list[ThreadingHTTPServer]" = []

    def start(self) -> "dict[str, int]":
        bound = {}
        for name, handler in (("metrics", self._metrics_handler()),
                              ("health", self._health_handler()),
                              ("webhook", self._webhook_handler())):
            port = self.ports[name]
            if port < 0:  # negative disables the listener
                continue
            srv = ThreadingHTTPServer(("0.0.0.0", port), handler)
            if name == "webhook" and self.tls_cert and self.tls_key:
                ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
                ctx.load_cert_chain(self.tls_cert, self.tls_key)
                srv.socket = ctx.wrap_socket(srv.socket, server_side=True)
            threading.Thread(target=srv.serve_forever, daemon=True,
                             name=f"serve-{name}").start()
            self._servers.append(srv)
            bound[name] = srv.server_address[1]
        self.bound = dict(bound)
        return bound

    def stop(self) -> None:
        for srv in self._servers:
            srv.shutdown()
            srv.server_close()  # release the listening socket now, not at GC
        self._servers.clear()

    # -- handlers --------------------------------------------------------------

    def _metrics_handler(self):
        op = self.operator

        class Metrics(_Base):
            def do_GET(self):
                if self.path.rstrip("/") in ("", "/metrics"):
                    return self._text(200, op.metrics_text(),
                                      content_type="text/plain; version=0.0.4")
                if self.path.startswith("/debug/statusz"):
                    # one consistent operator snapshot (introspect/statusz) —
                    # `python -m karpenter_tpu statusz` pretty-prints this
                    from .introspect import snapshot

                    return self._text(
                        200, json.dumps(snapshot(op), default=str),
                        content_type="application/json")
                if self.path.startswith("/debug/bundle"):
                    # live diagnostics bundle (no disk write) — the
                    # `diagnose` CLI's fetch side; ?decisions=N bounds the
                    # explain-ring tail carried along (clamped like
                    # /debug/traces ?limit=)
                    from urllib.parse import parse_qs, urlsplit

                    from .introspect.flightrecorder import BUNDLE_DECISIONS

                    fr = getattr(op, "flightrecorder", None)
                    if fr is None:
                        return self._text(404, "flight recorder not wired")
                    qs = parse_qs(urlsplit(self.path).query)
                    decisions = clamped_int_param(
                        qs, "decisions", BUNDLE_DECISIONS, MAX_DECISIONS)
                    if decisions is None:
                        return self._text(400,
                                          "decisions must be an integer")
                    return self._text(
                        200, json.dumps(
                            fr.bundle("manual", "GET /debug/bundle",
                                      decisions=decisions),
                            default=str),
                        content_type="application/json")
                if self.path.startswith("/debug/fleetz"):
                    # cross-replica joined snapshot (introspect/fleetview):
                    # per-replica health/epoch/residency/tenants + the
                    # router's tenant pinning, one schema-versioned doc
                    fv = getattr(op, "fleetview", None)
                    if fv is None:
                        return self._text(404, "fleet view not wired")
                    return self._text(
                        200, json.dumps(fv.fleetz(), default=str),
                        content_type="application/json")
                if self.path.startswith("/debug/traces"):
                    # recent traces as JSON; ?id=<trace_id> exports ONE trace
                    # in Chrome trace_event format (load in Perfetto /
                    # chrome://tracing) — federated across replicas when a
                    # fleet view is wired; ?id=&format=spans returns the raw
                    # span dicts (the fetch side of federation); ?index=1
                    # lists ids only (root, duration, tenant/replica
                    # annotations); ?limit=N bounds the listing
                    from urllib.parse import parse_qs, urlsplit

                    from .tracing import TRACER

                    qs = parse_qs(urlsplit(self.path).query)
                    trace_id = qs.get("id", [None])[0]
                    fv = getattr(op, "fleetview", None)
                    if trace_id:
                        if qs.get("format", [""])[0] == "spans":
                            spans = TRACER.trace(trace_id)
                            if not spans:
                                return self._text(404, "unknown trace id")
                            # the serving process's REAL pid rides along so
                            # a federating client (fleetview) lanes this
                            # replica's spans under its actual OS process
                            return self._text(
                                200, json.dumps(
                                    {"trace_id": trace_id,
                                     "pid": os.getpid(), "spans": spans},
                                    default=str),
                                content_type="application/json")
                        # chrome-trace exports carry the continuous
                        # profiler's samples as a `profiling` process lane
                        # (no-op while the profiling plane is disabled)
                        from .profiling import merge_chrome
                        if fv is not None:
                            doc = fv.federated_trace(trace_id)
                            if doc is None:
                                return self._text(404, "unknown trace id")
                            return self._text(
                                200, json.dumps(merge_chrome(doc),
                                                default=str),
                                content_type="application/json")
                        if not TRACER.trace(trace_id):
                            return self._text(404, "unknown trace id")
                        return self._text(
                            200, json.dumps(
                                merge_chrome(TRACER.chrome_trace(trace_id)),
                                default=str),
                            content_type="application/json")
                    limit = clamped_int_param(qs, "limit", 20,
                                              MAX_TRACE_LIMIT)
                    if limit is None:
                        return self._text(400, "limit must be an integer")
                    if qs.get("index", [""])[0]:
                        index = (fv.trace_index(limit) if fv is not None
                                 else TRACER.trace_index(limit))
                        return self._text(
                            200, json.dumps({"traces": index}, default=str),
                            content_type="application/json")
                    return self._text(
                        200, json.dumps({"traces": TRACER.traces(limit)},
                                        default=str),
                        content_type="application/json")
                if self.path.startswith("/debug/profilez"):
                    # continuous-profiler read surface: ?format=json is the
                    # pprof-style aggregation (stacks + device ladder + gap
                    # ledger), ?format=folded is flamegraph-ready folded
                    # stacks; ?n= bounds the stack listing (clamped like
                    # /debug/traces ?limit=)
                    from urllib.parse import parse_qs, urlsplit

                    from . import profiling

                    qs = parse_qs(urlsplit(self.path).query)
                    n = clamped_int_param(qs, "n", 100, MAX_PROFILE_STACKS)
                    if n is None:
                        return self._text(400, "n must be an integer")
                    fmt = qs.get("format", ["json"])[0]
                    if fmt not in ("json", "folded"):
                        return self._text(
                            400, f"unknown format: {fmt} (json|folded)")
                    # reading the endpoint is the always-on profiler's lazy
                    # ignition (no-op while the plane is disabled)
                    profiling.PROFILER.ensure_started()
                    if fmt == "folded":
                        return self._text(200, profiling.folded_text(n) + "\n")
                    return self._text(
                        200, json.dumps(profiling.profilez(n), default=str),
                        content_type="application/json")
                if self.path.startswith("/debug/criticalz"):
                    # critical-path read surface (ISSUE 18): per-solve
                    # interval analyses — chain length, overlap ratio,
                    # on/off-critical phase split, wait breakdown, plus
                    # the measured-roofline rung table; ?n= bounds the
                    # row listing (clamped like /debug/profilez ?n=)
                    from urllib.parse import parse_qs, urlsplit

                    from .profiling import critical

                    qs = parse_qs(urlsplit(self.path).query)
                    n = clamped_int_param(qs, "n", 50, MAX_CRITICAL_ROWS)
                    if n is None:
                        return self._text(400, "n must be an integer")
                    return self._text(
                        200, json.dumps(critical.criticalz(n), default=str),
                        content_type="application/json")
                if self.path.startswith("/debug/decisions"):
                    # decision-provenance ring (the explain plane): index
                    # of recent DecisionRecords; ?id= returns one record in
                    # full, ?pod= resolves the newest record mentioning the
                    # pod (the `explain <pod>` CLI's fetch side); ?kind=
                    # filters the index, ?limit= bounds it (clamped like
                    # /debug/traces ?limit=)
                    from urllib.parse import parse_qs, urlsplit

                    from . import explain

                    qs = parse_qs(urlsplit(self.path).query)
                    rid = qs.get("id", [None])[0]
                    if rid:
                        rec = explain.DECISIONS.get(rid)
                        if rec is None:
                            return self._text(404, "unknown decision id")
                        return self._text(
                            200, json.dumps(rec, default=str),
                            content_type="application/json")
                    pod = qs.get("pod", [None])[0]
                    if pod:
                        rec = explain.DECISIONS.find_pod(pod)
                        if rec is None:
                            return self._text(
                                404,
                                f"no decision record mentions pod {pod}")
                        return self._text(
                            200, json.dumps(rec, default=str),
                            content_type="application/json")
                    limit = clamped_int_param(qs, "limit", 50,
                                              MAX_DECISIONS)
                    if limit is None:
                        return self._text(400, "limit must be an integer")
                    kind = qs.get("kind", [None])[0]
                    index = [
                        {"id": r.get("id"), "kind": r.get("kind"),
                         "ts": r.get("ts"), "trace_id": r.get("trace_id")}
                        for r in explain.DECISIONS.records(limit,
                                                           kind=kind)]
                    return self._text(
                        200, json.dumps(
                            {"enabled": explain.enabled(),
                             "schema": explain.SCHEMA_VERSION,
                             "decisions": index}, default=str),
                        content_type="application/json")
                return self._text(404, "not found")

        return Metrics

    def _health_handler(self):
        op = self.operator

        class Health(_Base):
            def do_GET(self):
                if self.path.startswith("/logz"):
                    # recent controller logs (utils/logring) — the `logs`
                    # CLI's kubectl-logs-shaped triage endpoint; ?level=
                    # filters by minimum severity, ?format=json returns the
                    # structured records (JSON lines, bundle-shaped)
                    from urllib.parse import parse_qs, urlsplit

                    from .utils import logring

                    qs = parse_qs(urlsplit(self.path).query)
                    try:
                        n = int(qs.get("n", ["500"])[0])
                    except ValueError:
                        n = 500
                    level = qs.get("level", [None])[0]
                    if level is not None:
                        try:
                            logring._levelno(level)
                        except ValueError:
                            return self._text(
                                400, f"unknown log level: {level}")
                    if qs.get("format", [""])[0] == "json":
                        lines = [json.dumps(r, default=str) for r in
                                 logring.dump_records(n, level)]
                    else:
                        lines = logring.dump(n, level)
                    return self._text(200, "\n".join(lines) + "\n")
                if self.path.startswith("/eventz"):
                    # recent recorded events (post-dedupe ring) — the
                    # `events` CLI endpoint, mirroring /logz + `logs`
                    from urllib.parse import parse_qs, urlsplit

                    qs = parse_qs(urlsplit(self.path).query)
                    n = clamped_int_param(qs, "n", 100, MAX_EVENTS)
                    if n is None:
                        return self._text(400, "n must be an integer")
                    events = [
                        {"ts": ts, "kind": e.kind, "reason": e.reason,
                         "object": e.object_ref, "message": e.message}
                        for ts, e in op.recorder.recent(n)]
                    return self._text(
                        200, json.dumps({"events": events}, default=str),
                        content_type="application/json")
                if self.path.startswith("/healthz"):
                    ok, detail = op.healthz(), "ok"
                elif self.path.startswith("/readyz"):
                    # watchdog-aggregated: a stalled reconcile loop makes
                    # the replica unready, and the body names it
                    readyz = getattr(op, "readyz", None)
                    if readyz is None:
                        ok, detail = op.healthz(), "ok"
                    else:
                        ok, detail = readyz()
                elif self.path.startswith("/livez"):
                    ok, detail = op.livez(), "ok"
                else:
                    return self._text(404, "not found")
                return self._text(200 if ok else 503,
                                  detail if ok else
                                  (detail if detail != "ok" else "unhealthy"))

        return Health

    def _webhook_handler(self):
        op = self.operator

        class Webhook(_Base):
            def do_POST(self):
                mutate = self.path.startswith("/mutate")
                if not mutate and not self.path.startswith("/validate"):
                    return self._text(404, "not found")
                length = self.headers.get("Content-Length")
                try:
                    # fail CLOSED on an unreadable body (absent/zero
                    # Content-Length, e.g. a proxy stripping it): an
                    # unverifiable object must not be admitted
                    if length is None or int(length) <= 0:
                        raise ValueError("missing or empty request body")
                    review = json.loads(self.rfile.read(int(length)))
                    resp = _admit_review(op, review, mutate=mutate)
                except Exception as e:  # malformed review: explicit denial
                    resp = _review_response("", False, f"bad request: {e}")
                body = json.dumps(resp).encode()
                self.send_response(200)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

        return Webhook


class _Base(BaseHTTPRequestHandler):
    protocol_version = "HTTP/1.1"

    def log_message(self, *a):
        pass

    def _text(self, code: int, body: str,
              content_type: str = "text/plain") -> None:
        data = body.encode()
        self.send_response(code)
        self.send_header("Content-Type", content_type)
        self.send_header("Content-Length", str(len(data)))
        self.end_headers()
        self.wfile.write(data)


def _review_response(uid: str, allowed: bool, message: str = "") -> dict:
    resp = {"apiVersion": "admission.k8s.io/v1", "kind": "AdmissionReview",
            "response": {"uid": uid, "allowed": allowed}}
    if message:
        resp["response"]["status"] = {"message": message, "code": 403}
    return resp


def _admit_review(operator, review: dict, mutate: bool = False) -> dict:
    """AdmissionReview request -> response via the Webhooks pipeline.

    /validate runs defaulting+validation and answers allowed/denied only;
    /mutate additionally returns the defaulted object as a whole-document
    JSONPatch (RFC 6902 `replace` at path "") so the knative-style
    defaulting half works through a real apiserver too."""
    import base64
    import copy

    from .coordination import serde
    from .webhooks import AdmissionError

    req = review.get("request") or {}
    uid = req.get("uid", "")
    plural = ((req.get("resource") or {}).get("resource") or "").lower()
    kind = _PLURALS.get(plural)
    if kind is None:
        return _review_response(uid, True)  # not a guarded kind: admit
    doc = req.get("object") or {}
    try:
        obj = serde.from_manifest(kind, doc)
        admitted = operator.webhooks.admit(kind, obj,
                                           req.get("operation", "CREATE"))
    except AdmissionError as e:
        return _review_response(uid, False, str(e))
    except Exception as e:  # unparseable object
        return _review_response(uid, False, f"invalid {kind} manifest: {e}")
    resp = _review_response(uid, True)
    if mutate:
        name = serde.manifest_name(doc) or getattr(admitted, "name", "")
        defaulted = serde.to_manifest(kind, name, admitted)
        # preserve the caller's metadata (labels/annotations/namespace the
        # serde round trip doesn't carry)
        merged_meta = copy.deepcopy(doc.get("metadata") or {})
        merged_meta.update(defaulted.get("metadata") or {})
        defaulted["metadata"] = merged_meta
        patch = [{"op": "replace", "path": "", "value": defaulted}]
        resp["response"]["patchType"] = "JSONPatch"
        resp["response"]["patch"] = base64.b64encode(
            json.dumps(patch).encode()).decode()
    return resp
