"""Stub cloud-API server: the wire-protocol reference implementation.

Serves the JSON-over-HTTP protocol that cloudbackend.HttpCloud speaks,
backed by any FakeCloud-surface object (normally fake/cloud.py's stateful
simulator — ICE pools, eventual consistency, MockedFunction fault
injection all work THROUGH the wire). Tests boot it on 127.0.0.1:0; a
deployment could equally run it as a sidecar adapter in front of a real
provisioning API.

Protocol:
  GET  /imds/region          -> {"region": ...}           (IMDS analogue)
  POST /api/<Action>  JSON   -> 200 JSON result
                              | 400 {"code", "message"[, "failed_pools"]}
                              | 500 {"code": "InternalError", ...}
  DescribeInstanceTypes with {"dry_run": true} -> 400 DryRunOperation
  (the connectivity probe contract, reference context.go:91-99).

Faults for retry testing: fail_next_with(status) makes the next N
requests return that HTTP status before the handler runs.
"""

from __future__ import annotations

import dataclasses
import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from ..fake.cloud import CreateFleetRequest, FleetOverride, LaunchTemplate
from ..utils import errors as cloud_errors

# CreateFleet token claimed but outcome not yet recorded (see dispatch)
_FLEET_IN_FLIGHT = object()


def _asdicts(items) -> "list[dict]":
    return [dataclasses.asdict(i) for i in items]


class CloudAPIServer:
    """ThreadingHTTPServer wrapper with a real port and clean shutdown."""

    def __init__(self, cloud, region: str = "us-test-1",
                 host: str = "127.0.0.1", port: int = 0):
        self.cloud = cloud
        self.region = region
        self._fail_next: "list[int]" = []  # pending injected HTTP statuses
        # client-token -> recorded outcome (reply dict, the raised
        # exception, or _FLEET_IN_FLIGHT while the first attempt runs)
        self._fleet_replies: "dict[str, object]" = {}
        self._lock = threading.Lock()
        outer = self

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"
            disable_nagle_algorithm = True

            def log_message(self, *a):  # quiet
                pass

            def do_GET(self):
                if self.path == "/imds/region":
                    self._reply(200, {"region": outer.region})
                else:
                    self._reply(404, {"code": "ResourceNotFound",
                                      "message": self.path})

            def do_POST(self):
                # drain the body FIRST: on an HTTP/1.1 keep-alive socket an
                # early reply that leaves body bytes unread corrupts the
                # framing of the next request on the same connection
                n = int(self.headers.get("Content-Length", 0))
                raw = self.rfile.read(n)
                with outer._lock:
                    injected = (outer._fail_next.pop(0)
                                if outer._fail_next else None)
                if injected is not None:
                    self._reply(injected, {"code": "InternalError",
                                           "message": "injected fault"})
                    return
                try:
                    payload = json.loads(raw or b"{}")
                except ValueError:
                    self._reply(400, {"code": "MalformedRequest",
                                      "message": "bad json"})
                    return
                action = self.path.rsplit("/", 1)[-1]
                try:
                    self._reply(200, outer.dispatch(action, payload))
                except cloud_errors.FleetError as e:
                    self._reply(400, {"code": e.code, "message": e.message,
                                      "failed_pools": [list(p) for p in
                                                       e.failed_pools]})
                except cloud_errors.CloudError as e:
                    self._reply(400, {"code": e.code, "message": e.message})
                except Exception as e:  # noqa: BLE001 — wire boundary
                    self._reply(500, {"code": "InternalError",
                                      "message": str(e)[:200]})

            def _reply(self, status: int, doc: dict):
                body = json.dumps(doc).encode()
                self.send_response(status)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

        self._httpd = ThreadingHTTPServer((host, port), Handler)
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, daemon=True)

    # -- lifecycle -----------------------------------------------------------

    @property
    def endpoint(self) -> str:
        host, port = self._httpd.server_address[:2]
        return f"http://{host}:{port}"

    def start(self) -> "CloudAPIServer":
        self._thread.start()
        return self

    def stop(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()

    def fail_next_with(self, status: int, times: int = 1) -> None:
        with self._lock:
            self._fail_next.extend([status] * times)

    # -- dispatch ------------------------------------------------------------

    def dispatch(self, action: str, p: dict) -> dict:
        cloud = self.cloud
        if action == "DescribeInstanceTypes":
            if p.get("dry_run"):
                # success-by-error: the connectivity probe contract
                raise cloud_errors.CloudError(
                    "DryRunOperation", "dry run succeeded")
            return {"instance_types": [t.name for t in
                                       getattr(cloud, "catalog", None).types]
                    if getattr(cloud, "catalog", None) else []}
        if action == "CreateFleet":
            # client-token dedupe (EC2 ClientToken semantics): a transport
            # retry whose first attempt launched but lost the response
            # replays the recorded result instead of double-launching. The
            # token is CLAIMED before dispatch: if the first attempt dies
            # between launching and replying (a 5xx out of the dispatch
            # path), its outcome — success or the exception itself — is
            # still on record, so the retry replays it instead of
            # relaunching. An exception proves nothing about whether
            # instances came up (fault injection can fire past the launch),
            # so failures are replayed too rather than treated as new.
            token = p.get("client_token", "")
            if token:
                with self._lock:
                    hit = self._fleet_replies.get(token)
                    if hit is None:
                        self._fleet_replies[token] = _FLEET_IN_FLIGHT
                        while len(self._fleet_replies) > 1024:  # bounded
                            self._fleet_replies.pop(
                                next(iter(self._fleet_replies)))
                if hit is _FLEET_IN_FLIGHT:
                    # concurrent duplicate: the first attempt hasn't
                    # recorded its outcome yet — fail retriably rather
                    # than race it into a second launch
                    raise cloud_errors.CloudError(
                        "IdempotentOperationInProgress",
                        f"client token {token!r} is still in flight")
                if hit is not None:
                    if isinstance(hit, Exception):
                        raise hit
                    return hit
            req = CreateFleetRequest(
                launch_template=p["launch_template"],
                overrides=[FleetOverride(**o) for o in p["overrides"]],
                capacity=p["capacity"], capacity_type=p["capacity_type"],
                tags=p.get("tags") or {}, image_id=p.get("image_id", ""),
                fleet_context=p.get("fleet_context", ""))
            try:
                resp = cloud.create_fleet(req)
                out = {"instance_ids": resp.instance_ids,
                       "errors": _asdicts(resp.errors)}
            except Exception as e:
                if token:
                    with self._lock:
                        self._fleet_replies[token] = e
                raise
            if token:
                with self._lock:
                    self._fleet_replies[token] = out
            return out
        if action == "DescribeInstances":
            return {"instances": _asdicts(cloud.describe_instances(p["ids"]))}
        if action == "CreateTags":
            cloud.create_tags(p["instance_id"], p["tags"])
            return {}
        if action == "DescribeInstancesByTag":
            return {"instances": _asdicts(
                cloud.describe_instances_by_tag(p["key"], p["value"]))}
        if action == "TerminateInstances":
            return {"states": [list(s) for s in
                               cloud.terminate_instances(p["ids"])]}
        if action == "CreateLaunchTemplate":
            cloud.create_launch_template(LaunchTemplate(**p))
            return {}
        if action == "DescribeLaunchTemplates":
            return {"launch_templates": _asdicts(cloud.describe_launch_templates(
                p.get("tag_key", ""), p.get("tag_value", "")))}
        if action == "DeleteLaunchTemplate":
            cloud.delete_launch_template(p["name"])
            return {}
        if action == "DescribeSubnets":
            return {"subnets": _asdicts(cloud.describe_subnets(p["selector"]))}
        if action == "DescribeSecurityGroups":
            return {"security_groups": _asdicts(
                cloud.describe_security_groups(p["selector"]))}
        if action == "DescribeImages":
            return {"images": _asdicts(cloud.describe_images(p["selector"]))}
        if action == "GetSSMParameter":
            return {"value": cloud.get_ssm_parameter(p["name"])}
        if action == "GetPrices":
            return {"prices": [[t, ct, z, price] for (t, ct, z), price
                               in cloud.get_prices().items()]}
        raise cloud_errors.CloudError("UnknownAction", action)
