"""HTTP cloud-backend driver (VERDICT r4 ask #4).

The production half of the L7 cloud-session boundary: until now every
deployment path terminated in the in-memory simulated backend
(fake/cloud.py). This package adds a real wire driver — session bootstrap,
region discovery, connectivity dry-run, retrying JSON-over-HTTP transport,
and error-taxonomy mapping — so the framework serializes real launch
requests over a socket. The server half (cloudbackend/server.py) is the
recorded/stub backend the driver is tested against; a real deployment
points the session at whatever endpoint speaks the same protocol.

Parity targets:
- session bootstrap + region discovery + EC2 connectivity dry-run:
  /root/reference/pkg/context/context.go:53-99 (NewOrDie: session with
  retryer, IMDS region fallback, checkEC2Connectivity DryRun probe,
  user-agent handler :84-89)
- error taxonomy mapping: /root/reference/pkg/errors/errors.go:52-79
  (IsNotFound / IsUnfulfillableCapacity / IsLaunchTemplateNotFound) —
  wire errors rehydrate into the SAME CloudError/FleetError types the
  providers and batchers already branch on (utils/errors.py), so every
  layer above the boundary is transport-agnostic.

The client implements the exact duck-typed surface of fake/cloud.py
FakeCloud — one shared contract suite (tests/test_cloudbackend.py) runs
against both, which is the proof the boundary holds.
"""

from __future__ import annotations

import dataclasses
import json
import os
import time
import urllib.error
import urllib.request
import uuid
from typing import Optional, Sequence

from ..fake.cloud import (CloudInstance, CreateFleetRequest,
                          CreateFleetResponse, FleetPoolError, Image,
                          LaunchTemplate, SecurityGroup, Subnet)
from ..utils import errors as cloud_errors

USER_AGENT = "karpenter-tpu/0.1"
DEFAULT_RETRIES = 3  # client.DefaultRetryer parity (context.go:58-60)
RETRY_BACKOFF_S = 0.05


class ConnectivityError(Exception):
    """Session bootstrap failed: endpoint unreachable or dry-run rejected
    (the reference treats this as fatal at boot, context.go:67-69)."""


class CloudSession:
    """Bootstrapped connection context for the HTTP backend.

    Construction performs the reference's NewOrDie sequence:
    1. resolve the region — explicit arg, else KARPENTER_TPU_REGION env,
       else the endpoint's metadata service (GET /imds/region — the IMDS
       analogue, context.go:61-65);
    2. dry-run connectivity probe (DescribeInstanceTypes with dry_run:
       the expected outcome is the DryRunOperation error code — an actual
       listing means the flag was ignored; anything else is a failed boot,
       context.go:91-99).
    """

    def __init__(self, endpoint: str, region: str = "",
                 retries: int = DEFAULT_RETRIES, timeout_s: float = 10.0,
                 clock=None, policy=None):
        self.endpoint = endpoint.rstrip("/")
        self.retries = retries
        self.timeout_s = timeout_s
        # resilience hooks: with a RetryPolicy, replays are budget-gated and
        # backoff is jittered + clock-injectable; without one, the legacy
        # linear backoff runs through the (injectable) clock so tests and the
        # chaos plane never touch the wall clock
        self.clock = clock
        self.policy = policy
        self.region = (region or os.environ.get("KARPENTER_TPU_REGION")
                       or self._discover_region())
        self.check_connectivity()

    # -- transport ----------------------------------------------------------------

    def call(self, action: str, payload: dict) -> dict:
        """POST /api/<action>; retry transient failures (connection errors
        and 5xx) with linear backoff; rehydrate structured cloud errors."""
        body = json.dumps(payload).encode()
        pol = self.policy
        if pol is not None and pol.breaker is not None \
                and not pol.breaker.allow():
            pol.retries_total.inc(dep=pol.dep, outcome="breaker_open")
            raise ConnectivityError(
                f"{action} rejected: cloud circuit breaker open")
        last: "Exception | None" = None
        try:
            for attempt in range(self.retries + 1):
                req = urllib.request.Request(
                    f"{self.endpoint}/api/{action}", data=body,
                    headers={"Content-Type": "application/json",
                             "User-Agent": USER_AGENT,
                             "X-Region": self.region or ""})
                try:
                    with urllib.request.urlopen(req,
                                                timeout=self.timeout_s) as r:
                        doc = json.loads(r.read() or b"{}")
                        if pol is not None:
                            pol.note_success()
                        return doc
                except urllib.error.HTTPError as e:
                    data = e.read()
                    if e.code >= 500:  # transient server side: retry
                        last = e
                    else:
                        # a structured error IS a live server: breaker
                        # success (mirrors the solver client's StaleSync
                        # handling) — without it the half-open probe the
                        # allow() above may have admitted would stay
                        # unjudged and wedge the shared cloud edge open
                        if pol is not None:
                            pol.note_success()
                        raise _rehydrate_error(data) from None
                except (urllib.error.URLError, TimeoutError, OSError) as e:
                    last = e
                if pol is not None:
                    pol.note_failure()
                if attempt < self.retries:
                    if pol is not None:
                        if not pol.try_retry():
                            break  # budget exhausted: give up now
                        pol.sleep_backoff()
                    else:
                        self._sleep(RETRY_BACKOFF_S * (attempt + 1))
            if pol is not None:
                pol.retries_total.inc(dep=pol.dep, outcome="give_up")
            raise ConnectivityError(
                f"{action} failed after {self.retries + 1} attempts: {last}")
        finally:
            # any exit that judged the call already resolved the probe
            # (release is then a no-op); unexpected raises (e.g. a body
            # decode error) must not leave it in flight
            if pol is not None:
                pol.release_probe()

    def _sleep(self, seconds: float) -> None:
        if self.clock is not None:
            self.clock.sleep(seconds)
        else:
            time.sleep(seconds)

    def _discover_region(self) -> str:
        """Metadata-service region discovery (IMDS analogue)."""
        req = urllib.request.Request(
            f"{self.endpoint}/imds/region",
            headers={"User-Agent": USER_AGENT})
        try:
            with urllib.request.urlopen(req, timeout=self.timeout_s) as r:
                return json.loads(r.read()).get("region", "")
        except (urllib.error.URLError, TimeoutError, OSError, ValueError) as e:
            raise ConnectivityError(
                f"region discovery against {self.endpoint} failed: {e}") from e

    def check_connectivity(self) -> None:
        """Dry-run DescribeInstanceTypes; success IS the DryRunOperation
        error (checkEC2Connectivity, context.go:91-99)."""
        try:
            self.call("DescribeInstanceTypes", {"dry_run": True})
        except cloud_errors.CloudError as e:
            if e.code == "DryRunOperation":
                return
            raise ConnectivityError(f"dry-run probe rejected: {e}") from e
        raise ConnectivityError(
            "dry-run probe returned data instead of DryRunOperation — "
            "endpoint ignored the dry_run flag")


def _rehydrate_error(data: bytes) -> Exception:
    """Wire error -> the taxonomy type the stack already branches on."""
    try:
        doc = json.loads(data)
    except ValueError:
        doc = {}
    code = doc.get("code", "InternalError")
    message = doc.get("message", "")
    pools = doc.get("failed_pools")
    if pools is not None:
        return cloud_errors.FleetError(
            code, [tuple(p) for p in pools], message)
    return cloud_errors.CloudError(code, message)


class HttpCloud:
    """FakeCloud-surface client over a CloudSession: the drop-in `cloud`
    object for providers, batchers, and the operator."""

    def __init__(self, session: CloudSession):
        self.session = session

    # -- fleet ---------------------------------------------------------------

    def create_fleet(self, request: CreateFleetRequest) -> CreateFleetResponse:
        # client token (EC2 ClientToken semantics): the transport retries
        # timeouts/5xx, and a retry of a CreateFleet whose RESPONSE was
        # lost must replay the first launch, not run a second one — the
        # server dedupes on the token (cloudbackend/server.py)
        payload = dataclasses.asdict(request)
        payload["client_token"] = uuid.uuid4().hex
        doc = self.session.call("CreateFleet", payload)
        return CreateFleetResponse(
            instance_ids=list(doc.get("instance_ids", ())),
            errors=[FleetPoolError(**e) for e in doc.get("errors", ())])

    def describe_instances(self, ids: Sequence[str]) -> "list[CloudInstance]":
        doc = self.session.call("DescribeInstances", {"ids": list(ids)})
        return [CloudInstance(**d) for d in doc.get("instances", ())]

    def create_tags(self, instance_id: str, tags: "dict[str, str]") -> None:
        self.session.call("CreateTags",
                          {"instance_id": instance_id, "tags": dict(tags)})

    def describe_instances_by_tag(self, key: str, value: str
                                  ) -> "list[CloudInstance]":
        doc = self.session.call("DescribeInstancesByTag",
                                {"key": key, "value": value})
        return [CloudInstance(**d) for d in doc.get("instances", ())]

    def terminate_instances(self, ids: Sequence[str]
                            ) -> "list[tuple[str, str]]":
        doc = self.session.call("TerminateInstances", {"ids": list(ids)})
        return [tuple(x) for x in doc.get("states", ())]

    # -- launch templates ----------------------------------------------------

    def create_launch_template(self, lt: LaunchTemplate) -> None:
        self.session.call("CreateLaunchTemplate", dataclasses.asdict(lt))

    def describe_launch_templates(self, tag_key: str = "",
                                  tag_value: str = "") -> "list[LaunchTemplate]":
        doc = self.session.call("DescribeLaunchTemplates",
                                {"tag_key": tag_key, "tag_value": tag_value})
        return [LaunchTemplate(**d) for d in doc.get("launch_templates", ())]

    def delete_launch_template(self, name: str) -> None:
        self.session.call("DeleteLaunchTemplate", {"name": name})

    # -- discovery -----------------------------------------------------------

    def describe_subnets(self, selector: "dict[str, str]") -> "list[Subnet]":
        doc = self.session.call("DescribeSubnets", {"selector": dict(selector)})
        return [Subnet(**d) for d in doc.get("subnets", ())]

    def describe_security_groups(self, selector: "dict[str, str]"
                                 ) -> "list[SecurityGroup]":
        doc = self.session.call("DescribeSecurityGroups",
                                {"selector": dict(selector)})
        return [SecurityGroup(**d) for d in doc.get("security_groups", ())]

    def describe_images(self, selector: "dict[str, str]") -> "list[Image]":
        doc = self.session.call("DescribeImages", {"selector": dict(selector)})
        return [Image(**d) for d in doc.get("images", ())]

    def get_ssm_parameter(self, name: str) -> str:
        return self.session.call("GetSSMParameter", {"name": name})["value"]

    def get_prices(self) -> "dict[tuple[str, str, str], float]":
        doc = self.session.call("GetPrices", {})
        return {(t, ct, z): p for t, ct, z, p in doc.get("prices", ())}


def connect(endpoint: str, region: str = "",
            retries: int = DEFAULT_RETRIES, clock=None,
            policy=None) -> HttpCloud:
    """Bootstrap a session (region discovery + connectivity dry-run) and
    return the drop-in cloud client. Raises ConnectivityError at boot the
    way the reference's NewOrDie is fatal (context.go:53)."""
    return HttpCloud(CloudSession(endpoint, region=region, retries=retries,
                                  clock=clock, policy=policy))
