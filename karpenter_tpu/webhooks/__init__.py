"""Admission webhooks: defaulting + validation at the API boundary.

Parity target: /root/reference/pkg/webhooks/webhooks.go:33-63 — knative
defaulting and validation admission controllers registered for the
AWSNodeTemplate and Provisioner kinds (the `Resources` map :60-63), plus the
core webhook half that defaults/validates the Provisioner CRD
(/root/reference/pkg/apis/v1alpha5/provisioner.go:34-60).

Shape here: the coordination plane (KubeStore, the kube-apiserver analogue)
calls Webhooks.admit() on every create/update of a registered kind — the same
interception point a real apiserver gives admission webhooks. Rejection
raises AdmissionError and the write never lands.
"""

from __future__ import annotations

import logging
from typing import Callable, Optional

from ..apis.nodetemplate import NodeTemplate
from ..apis.provisioner import Provisioner, ValidationError

log = logging.getLogger("karpenter.webhooks")


class AdmissionError(Exception):
    """Write rejected by a validation webhook."""


class Webhooks:
    """Defaulting-then-validation pipeline per registered kind
    (webhooks.go Resources map analogue)."""

    def __init__(self, cluster_name: str = ""):
        # kind -> (defaulter, validator); mirrors the reference's
        # {AWSNodeTemplate, Provisioner} registration. cluster_name feeds
        # the per-cluster restricted ownership tag check (tags.go:29+,
        # kubernetes.io/cluster/<name> is karpenter-owned).
        self.cluster_name = cluster_name
        self.resources: "dict[str, tuple[Optional[Callable], Optional[Callable]]]" = {
            "provisioners": (self._default_provisioner, self._validate_provisioner),
            "nodetemplates": (self._default_nodetemplate, self._validate_nodetemplate),
        }

    def admit(self, kind: str, obj, operation: str = "CREATE"):
        """Run defaulting then validation; returns the (mutated) object.
        Raises AdmissionError on rejection."""
        entry = self.resources.get(kind)
        if entry is None:
            return obj
        defaulter, validator = entry
        if defaulter is not None:
            defaulter(obj)
        if validator is not None:
            try:
                validator(obj)
            except (ValidationError, ValueError) as e:
                raise AdmissionError(f"{kind} admission denied ({operation}): {e}")
        return obj

    # -- per-kind hooks (delegating to the API types' own spec logic) --------------

    @staticmethod
    def _default_provisioner(p: Provisioner) -> None:
        p.set_defaults()

    @staticmethod
    def _validate_provisioner(p: Provisioner) -> None:
        p.validate()

    @staticmethod
    def _default_nodetemplate(t: NodeTemplate) -> None:
        t.set_defaults()

    def _validate_nodetemplate(self, t: NodeTemplate) -> None:
        t.validate(cluster_name=self.cluster_name)
